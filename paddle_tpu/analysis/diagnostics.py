"""Structured diagnostics for the jaxpr static analyzer.

The analog of the reference's compile-time Program validation output
(operator registry attr checks raise EnforceNotMet with an op context);
here every finding is a structured record so the CLI can render text or
JSON and CI can gate on severity without parsing messages.
"""

import json

ERROR = "error"
WARNING = "warning"
INFO = "info"

_SEVERITY_RANK = {INFO: 0, WARNING: 1, ERROR: 2}


def severity_rank(sev):
    try:
        return _SEVERITY_RANK[sev]
    except KeyError:
        raise ValueError("unknown severity %r (use %s)"
                         % (sev, "/".join(_SEVERITY_RANK)))


class Diagnostic:
    """One finding: rule id + severity + op path + message (+ fix hint).

    ``path`` is the op path of the offending eqn — the executor lowers
    every Program op under a ``jax.named_scope("<op_type>.<seq>")``, so
    paths read like ``scan[3]/fc.12/dot_general`` and point back to the
    Program op that produced the jaxpr region.
    """

    __slots__ = ("rule", "severity", "message", "path", "hint", "model",
                 "cost_flops")

    def __init__(self, rule, severity, message, path="", hint="",
                 model="", cost_flops=None):
        severity_rank(severity)  # validate
        self.rule = rule
        self.severity = severity
        self.message = message
        self.path = path
        self.hint = hint
        self.model = model
        self.cost_flops = cost_flops

    def to_dict(self):
        d = {"rule": self.rule, "severity": self.severity,
             "message": self.message, "path": self.path}
        if self.hint:
            d["hint"] = self.hint
        if self.model:
            d["model"] = self.model
        if self.cost_flops is not None:
            d["cost_flops"] = self.cost_flops
        return d

    def __repr__(self):
        return "Diagnostic(%s, %s, %r)" % (self.rule, self.severity,
                                           self.message)


class Report:
    """Diagnostics from one ``check_program`` run (or a merged zoo run)."""

    def __init__(self, diagnostics=(), model=""):
        self.model = model
        self.diagnostics = list(diagnostics)

    def __iter__(self):
        return iter(self.diagnostics)

    def __len__(self):
        return len(self.diagnostics)

    def extend(self, other):
        self.diagnostics.extend(other)
        return self

    def by_severity(self, severity):
        return [d for d in self.diagnostics if d.severity == severity]

    def at_least(self, severity):
        floor = severity_rank(severity)
        return [d for d in self.diagnostics
                if severity_rank(d.severity) >= floor]

    def counts(self):
        c = {ERROR: 0, WARNING: 0, INFO: 0}
        for d in self.diagnostics:
            c[d.severity] += 1
        return c

    def render_text(self, verbose=False):
        lines = []
        order = sorted(self.diagnostics,
                       key=lambda d: (-severity_rank(d.severity),
                                      d.model, d.rule))
        for d in order:
            if not verbose and d.severity == INFO:
                continue
            loc = " @ %s" % d.path if d.path else ""
            tag = ("[%s]" % d.model) if d.model else ""
            lines.append("%-7s %s %s: %s%s"
                         % (d.severity.upper(), tag, d.rule, d.message,
                            loc))
            if d.hint:
                lines.append("        hint: %s" % d.hint)
        c = self.counts()
        lines.append("-- %d error(s), %d warning(s), %d info"
                     % (c[ERROR], c[WARNING], c[INFO]))
        return "\n".join(lines)

    def to_json(self):
        return json.dumps(
            {"model": self.model, "counts": self.counts(),
             "diagnostics": [d.to_dict() for d in self.diagnostics]},
            indent=2, sort_keys=True)
