/* C deployment smoke test: load a saved inference model and run one
 * forward pass from pure C (the reference's capi/examples role).
 * Usage: test_capi <model_dir> <feature_dim>  — prints OUT followed by the
 * output values for an all-ones input row. */
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>

extern void* pt_predictor_create(const char* model_dir);
extern int pt_predictor_run(void* p, const float* in, const int64_t* shape,
                            int nd, float* out, int64_t out_cap,
                            int64_t* out_shape, int* out_nd);
extern void pt_predictor_destroy(void* p);
extern const char* pt_last_error(void);

int main(int argc, char** argv) {
  if (argc < 3) {
    fprintf(stderr, "usage: %s <model_dir> <dim>\n", argv[0]);
    return 2;
  }
  int dim = atoi(argv[2]);
  if (dim < 1 || dim > 512) {
    fprintf(stderr, "dim must be in [1, 512]\n");
    return 2;
  }
  void* p = pt_predictor_create(argv[1]);
  if (!p) {
    fprintf(stderr, "create failed: %s\n", pt_last_error());
    return 1;
  }
  float in[512];
  for (int i = 0; i < dim; ++i) in[i] = 1.0f;
  int64_t shape[2] = {1, dim};
  float out[512];
  int64_t out_shape[8];
  int out_nd = 0;
  if (pt_predictor_run(p, in, shape, 2, out, 512, out_shape, &out_nd)) {
    fprintf(stderr, "run failed: %s\n", pt_last_error());
    return 1;
  }
  int64_t n = 1;
  for (int i = 0; i < out_nd; ++i) n *= out_shape[i];
  printf("OUT");
  for (int64_t i = 0; i < n; ++i) printf(" %.6f", out[i]);
  printf("\n");
  pt_predictor_destroy(p);
  return 0;
}
