"""Native (C++) runtime components, built lazily with the in-tree
Makefile and bound via ctypes (pybind11 is not available in this image;
the C ABI keeps the boundary minimal anyway).

Components:
- librecordio.so — chunked+CRC+DEFLATE record file format
  (recordio/recordio.cc; reference capability paddle/fluid/recordio/).
"""

import ctypes
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_BUILD = os.path.join(_DIR, "build")
_LOCK = threading.Lock()
_LIBS = {}


class NativeBuildError(RuntimeError):
    pass


def _build(target):
    try:
        subprocess.run(
            ["make", "-C", _DIR, os.path.join("build", target)],
            check=True, capture_output=True, text=True)
    except (OSError, subprocess.CalledProcessError) as e:
        out = getattr(e, "stderr", "") or str(e)
        raise NativeBuildError(
            "failed to build native %s (need g++ and zlib): %s"
            % (target, out.strip()[-800:])) from e


def load(name):
    """Load (building if necessary) lib<name>.so; cached per process."""
    with _LOCK:
        if name in _LIBS:
            return _LIBS[name]
        target = "lib%s.so" % name
        path = os.path.join(_BUILD, target)
        src = os.path.join(_DIR, name, "%s.cc" % name)
        if not os.path.exists(path) or (
                os.path.exists(src)
                and os.path.getmtime(src) > os.path.getmtime(path)):
            _build(target)
        lib = ctypes.CDLL(path)
        _LIBS[name] = lib
        return lib
