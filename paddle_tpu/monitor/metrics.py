"""Thread-safe metrics registry: counters, gauges, histograms with labels.

The runtime half of the observability story (the static half is
paddle_tpu.analysis). Reference parity: the 2018 framework had no
metrics registry at all — its closest analogs are the profiler's
per-event count/total table (platform/profiler.h) and the pserver's
ad-hoc stderr logs; here every subsystem (executor, distributed runtime,
watchdog) reports into ONE process-wide registry that exports Prometheus
text or a JSON snapshot at any moment, the always-on production shape.

Design: metric objects are cheap to update (one lock + dict store per
observation — sub-microsecond, invisible next to a training step or a
socket round-trip) and are safe to create at import time; creating a
metric never starts threads or touches files. `registry()` returns the
process default; tests may build private `Registry()` instances.
"""

import bisect
import copy
import json
import threading
import time
import uuid

__all__ = ["Counter", "Gauge", "Histogram", "Registry", "registry",
           "bucket_percentile", "merge_snapshots", "META_KEY",
           "render_prometheus_snapshot"]

# Reserved snapshot key carrying registry identity (never a metric name
# — metric names are prometheus identifiers, so the collision is
# impossible by construction). The fleet collector keys restart
# detection and same-process dedup on the incarnation in here.
META_KEY = "__meta__"

# Multi-label series keys join their label values with the ASCII unit
# separator (JSON-safe, never in a printable label value) so the
# renderer can split them back LOSSLESSLY — a "," join would
# mis-attribute any comma-bearing value. Single-label keys are the
# bare value (the schema every existing consumer reads); legacy
# ","-joined multi-label keys from older snapshots still split on ",".
_KEY_SEP = "\x1f"

# step latencies span ~100us (tiny CPU graphs) to minutes (first XLA
# compile included in a run() call); exponential buckets, factor ~2.
DEFAULT_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0)


def _merge_hist_ent(dst, src):
    """Accumulate one histogram series entry ({counts, sum, count})
    into another, bucket-wise — the ONE merge arithmetic behind both
    Histogram.merge (object level) and merge_snapshots (dict level)."""
    for i, c in enumerate(src["counts"]):
        dst["counts"][i] += int(c)
    dst["sum"] += float(src["sum"])
    dst["count"] += int(src["count"])


def bucket_percentile(buckets, counts, q):
    """Bucket-interpolated q-quantile (0..1) over NON-cumulative
    per-bucket counts (``len(buckets) + 1`` entries, overflow last);
    None when empty. ONE algorithm shared by ``Histogram.percentile``
    (live) and the SLO evaluator's offline snapshot math
    (paddle_tpu/slo.py) — a fix to either must be a fix to both, or
    --metrics verdicts drift from live percentiles."""
    total = sum(counts)
    if not total:
        return None
    target = q * total
    acc = 0.0
    for i, c in enumerate(counts):
        if acc + c >= target and c:
            lo = buckets[i - 1] if i > 0 else 0.0
            hi = buckets[i] if i < len(buckets) else buckets[-1]
            frac = (target - acc) / c
            return lo + (hi - lo) * min(1.0, max(0.0, frac))
        acc += c
    return buckets[-1]


def _label_key(label_names, labels):
    if set(labels) != set(label_names):
        raise ValueError(
            "metric labels %s do not match declared %s"
            % (sorted(labels), sorted(label_names)))
    return tuple(str(labels[n]) for n in label_names)


class _Metric:
    kind = "untyped"

    def __init__(self, name, help_="", label_names=()):
        self.name = name
        self.help = help_
        self.label_names = tuple(label_names)
        self._lock = threading.Lock()
        self._series = {}       # label-value tuple -> stored value

    def _snapshot_ent(self):
        """This metric as one snapshot-dict entry — the ONE shape the
        registry snapshot, the fleet collector's merge, and the
        Prometheus renderer all share."""
        ent = {"kind": self.kind, "help": self.help,
               "labels": list(self.label_names),
               "series": {_KEY_SEP.join(k): v
                          for k, v in self.snapshot().items()}}
        if self.kind == "histogram":
            ent["buckets"] = list(self.buckets)
        return ent

    def clear(self):
        with self._lock:
            self._series.clear()


class Counter(_Metric):
    """Monotonically increasing count (resets only with the process)."""

    kind = "counter"

    def inc(self, value=1, **labels):
        if value < 0:
            raise ValueError("counter increment must be >= 0")
        key = _label_key(self.label_names, labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0) + value

    def value(self, **labels):
        key = _label_key(self.label_names, labels)
        with self._lock:
            return self._series.get(key, 0)

    def snapshot(self):
        with self._lock:
            return dict(self._series)


class Gauge(_Metric):
    """Point-in-time value (can go up and down)."""

    kind = "gauge"

    def set(self, value, **labels):
        key = _label_key(self.label_names, labels)
        with self._lock:
            self._series[key] = float(value)

    def inc(self, value=1, **labels):
        key = _label_key(self.label_names, labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + value

    def value(self, **labels):
        key = _label_key(self.label_names, labels)
        with self._lock:
            return self._series.get(key)

    def snapshot(self):
        with self._lock:
            return dict(self._series)


class Histogram(_Metric):
    """Cumulative-bucket histogram (Prometheus semantics) with a cheap
    bucket-interpolated percentile for in-process reporting."""

    kind = "histogram"

    def __init__(self, name, help_="", label_names=(), buckets=None):
        super().__init__(name, help_, label_names)
        self.buckets = tuple(sorted(buckets or DEFAULT_BUCKETS))

    def observe(self, value, **labels):
        key = _label_key(self.label_names, labels)
        with self._lock:
            ent = self._series.get(key)
            if ent is None:
                ent = self._series[key] = {
                    "counts": [0] * (len(self.buckets) + 1),
                    "sum": 0.0, "count": 0}
            idx = bisect.bisect_left(self.buckets, value)
            ent["counts"][idx] += 1
            ent["sum"] += float(value)
            ent["count"] += 1

    def count(self, **labels):
        key = _label_key(self.label_names, labels)
        with self._lock:
            ent = self._series.get(key)
            return ent["count"] if ent else 0

    def sum(self, **labels):
        key = _label_key(self.label_names, labels)
        with self._lock:
            ent = self._series.get(key)
            return ent["sum"] if ent else 0.0

    def percentile(self, q, **labels):
        """Approximate q-quantile (0..1) by linear interpolation inside
        the containing bucket. None when empty."""
        key = _label_key(self.label_names, labels)
        with self._lock:
            ent = self._series.get(key)
            if not ent or not ent["count"]:
                return None
            counts = list(ent["counts"])
        return bucket_percentile(self.buckets, counts, q)

    def snapshot(self):
        with self._lock:
            return {k: {"counts": list(v["counts"]), "sum": v["sum"],
                        "count": v["count"]}
                    for k, v in self._series.items()}

    def merge(self, other):
        """Merge another Histogram's observations into this one,
        bucket-wise (the fleet-collector primitive: two processes'
        snapshots of the SAME histogram sum exactly because every
        process embeds identical bucket boundaries). Mismatched
        boundaries are a schema violation — a silent elementwise sum
        would produce a histogram whose percentiles mean nothing, so
        it raises instead."""
        if tuple(other.buckets) != self.buckets:
            raise ValueError(
                "histogram %r bucket boundaries differ: %s vs %s"
                % (self.name, self.buckets, tuple(other.buckets)))
        for key, ent in other.snapshot().items():
            with self._lock:
                mine = self._series.get(key)
                if mine is None:
                    mine = self._series[key] = {
                        "counts": [0] * (len(self.buckets) + 1),
                        "sum": 0.0, "count": 0}
                _merge_hist_ent(mine, ent)


class Registry:
    """Named collection of metrics. get-or-create semantics: asking for
    an existing name with the same type and labels returns the SAME
    object (so modules can declare their metrics independently); a
    conflicting re-registration raises."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics = {}
        # registry identity, stamped into every snapshot: the
        # incarnation changes when the process (or a test's private
        # Registry) is recreated, and uptime_s is monotonic within one
        # incarnation — together they let a fleet collector tell a
        # counter RESET (process restart) from counter progress, so
        # deltas never go negative across a respawn.
        self.incarnation = uuid.uuid4().hex[:16]
        self._t0 = time.monotonic()

    def uptime_s(self):
        """Seconds since this registry (≈ this process) came up."""
        return time.monotonic() - self._t0

    def _get_or_create(self, cls, name, help_, label_names, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if type(m) is not cls or \
                        m.label_names != tuple(label_names):
                    raise ValueError(
                        "metric %r already registered as %s%s"
                        % (name, type(m).__name__, m.label_names))
                want_buckets = kw.get("buckets")
                if want_buckets is not None and \
                        m.buckets != tuple(sorted(want_buckets)):
                    raise ValueError(
                        "histogram %r already registered with buckets %s"
                        % (name, m.buckets))
                return m
            m = cls(name, help_, label_names, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name, help_="", label_names=()):
        return self._get_or_create(Counter, name, help_, label_names)

    def gauge(self, name, help_="", label_names=()):
        return self._get_or_create(Gauge, name, help_, label_names)

    def histogram(self, name, help_="", label_names=(), buckets=None):
        return self._get_or_create(Histogram, name, help_, label_names,
                                   buckets=buckets)

    def get(self, name):
        with self._lock:
            return self._metrics.get(name)

    def names(self):
        with self._lock:
            return sorted(self._metrics)

    def snapshot(self):
        """{name: {"kind", "labels", "series": {"l1,l2": value}}} — the
        JSON-able dump the flight recorder and watchdog embed.
        Histograms additionally carry their "buckets" boundaries so a
        dumped snapshot stays percentile-evaluable offline (the SLO
        engine's --metrics source). The reserved ``__meta__`` entry
        stamps the registry's incarnation and monotonic uptime so a
        scraper can detect process restarts (counter resets)."""
        # ONE lock acquisition covers the incarnation stamp AND the
        # series reads (lock order registry -> metric, same as
        # reset()): a reset racing this snapshot can never produce
        # the old incarnation stamped onto cleared counters — which a
        # collector would re-base and then double-merge.
        with self._lock:
            out = {META_KEY: {"incarnation": self.incarnation,
                              "uptime_s": self.uptime_s(),
                              "ts": time.time()}}
            for m in self._metrics.values():
                out[m.name] = m._snapshot_ent()
        return out

    def render_prometheus(self):
        # ONE exposition implementation for the per-process export
        # and the fleet collector's merged re-export
        return render_prometheus_snapshot(self.snapshot())

    def dump_json(self, path):
        with open(path, "w") as f:
            json.dump(self.snapshot(), f, indent=1, sort_keys=True)

    def reset(self):
        """Clear every series (metric objects survive — references held
        by modules stay valid). Test isolation helper. The incarnation
        is rolled: to any scraper a reset IS a restart (every counter
        returns to zero), and the new incarnation keeps its deltas from
        going negative."""
        with self._lock:
            # roll AND clear under the registry lock: snapshot()
            # takes the same lock, so no scraper can observe the new
            # incarnation stamped onto pre-reset totals (which a
            # collector would double-merge as a fresh process's).
            # Lock order registry -> metric matches snapshot()'s.
            self.incarnation = uuid.uuid4().hex[:16]
            self._t0 = time.monotonic()
            for m in self._metrics.values():
                m.clear()


def render_prometheus_snapshot(snap):
    """Prometheus text exposition of a snapshot dict — THE format
    implementation, shared by ``Registry.render_prometheus`` (one
    process) and the fleet collector's merged re-export
    (``monitor.collector``)."""
    lines = []
    for name in sorted(snap):
        if name == META_KEY:
            continue
        ent = snap[name]
        kind = ent.get("kind", "untyped")
        labels = list(ent.get("labels", ()))
        if "help" in ent:
            lines.append("# HELP %s %s" % (name, ent["help"]))
        lines.append("# TYPE %s %s" % (name, kind))

        def fmt(key, extra=()):
            # a single-label metric's series key IS the label value
            # (empty string included — it must still render the
            # label, not collide with an unlabeled series);
            # multi-label keys split on the lossless unit separator
            # (legacy ","-joined snapshots on disk fall back to ",")
            if not labels:
                vals = []
            elif len(labels) == 1:
                vals = [key]
            elif _KEY_SEP in key or not key:
                vals = key.split(_KEY_SEP)
            else:
                vals = key.split(",")
            pairs = list(zip(labels, vals)) + list(extra)
            if not pairs:
                return ""
            return "{%s}" % ",".join(
                '%s="%s"' % (k, str(v).replace('"', r'\"'))
                for k, v in pairs)

        for key, v in sorted(ent.get("series", {}).items()):
            if kind == "histogram":
                acc = 0
                for b, c in zip(ent.get("buckets", ()), v["counts"]):
                    acc += c
                    lines.append("%s_bucket%s %d" % (
                        name, fmt(key, [("le", repr(float(b)))]),
                        acc))
                lines.append("%s_bucket%s %d" % (
                    name, fmt(key, [("le", "+Inf")]), v["count"]))
                lines.append("%s_sum%s %s" % (name, fmt(key),
                                              v["sum"]))
                lines.append("%s_count%s %d" % (name, fmt(key),
                                                v["count"]))
            else:
                lines.append("%s%s %s" % (name, fmt(key), v))
    return "\n".join(lines) + "\n"


def merge_snapshots(into, src):
    """Merge one ``Registry.snapshot()``-shaped dict into another,
    IN PLACE (``into`` is mutated and returned) — the fleet
    collector's accumulation primitive, unit-testable without
    sockets:

      * counters / gauges: exact per-series sum,
      * histograms: bucket-wise count sum (+ sum/count), after
        checking the embedded boundaries match — mismatched buckets
        raise loudly instead of producing meaningless percentiles,
      * the reserved ``__meta__`` entry of ``src`` is ignored
        (``into`` keeps its own, if any).

    Metrics present in only one snapshot pass through unchanged; a
    name carried with DIFFERENT kinds on the two sides is a schema
    violation and raises — BEFORE any mutation (validate-then-apply),
    so a failed merge never leaves ``into`` half-merged (the fleet
    accumulator would double-count on retry otherwise)."""
    for name, ent in src.items():
        if name == META_KEY:
            continue
        mine = into.get(name)
        if mine is None:
            continue
        if mine.get("kind") != ent.get("kind"):
            raise ValueError(
                "metric %r kind mismatch: %r vs %r"
                % (name, mine.get("kind"), ent.get("kind")))
        if ent.get("kind") == "histogram" and \
                list(mine.get("buckets", ())) != \
                list(ent.get("buckets", ())):
            raise ValueError(
                "histogram %r bucket boundaries differ: %s vs %s"
                % (name, mine.get("buckets"), ent.get("buckets")))
    for name, ent in src.items():
        if name == META_KEY:
            continue
        mine = into.get(name)
        if mine is None:
            into[name] = copy.deepcopy(ent)
            continue
        if ent.get("kind") == "histogram":
            for key, s in ent["series"].items():
                m = mine["series"].get(key)
                if m is None:
                    mine["series"][key] = copy.deepcopy(s)
                    continue
                _merge_hist_ent(m, s)
        else:
            for key, v in ent["series"].items():
                mine["series"][key] = mine["series"].get(key, 0) + v
    return into


_default = Registry()


def registry():
    """The process-wide default registry."""
    return _default
