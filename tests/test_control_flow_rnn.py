"""Control-flow + RNN tests: recurrent/scan, while, lstm/gru, DynamicRNN."""

import numpy as np
import pytest

import paddle_tpu as fluid


def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


def test_static_rnn_accumulator_matches_numpy():
    # time-major input [T, B, D]; step: h = h_prev * decay + x_t @ I
    x = fluid.layers.data("x", [3, 4], append_batch_size=False)
    x3 = fluid.layers.reshape(x, [5, 3, 4])   # dummy reshape to [T,B,D]
    rnn = fluid.layers.StaticRNN()
    with rnn.step():
        x_t = rnn.step_input(x3)
        h_prev = rnn.memory(shape=[-1, 4], batch_ref=x_t, init_value=0.0)
        h = fluid.layers.scale(h_prev, 0.5) + x_t
        rnn.update_memory(h_prev, h)
        rnn.step_output(h)
    out = rnn()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    xv = np.random.RandomState(0).rand(15, 4).astype(np.float32)
    got, = exe.run(feed={"x": xv}, fetch_list=[out])
    xs = xv.reshape(5, 3, 4)
    h = np.zeros((3, 4), np.float32)
    want = []
    for t in range(5):
        h = h * 0.5 + xs[t]
        want.append(h.copy())
    np.testing.assert_allclose(got, np.stack(want), rtol=1e-5)


def test_static_rnn_trains():
    x = fluid.layers.data("x", [6, 8])          # [B, T, D] batch-major
    label = fluid.layers.data("label", [1], dtype="int64")
    xt = fluid.layers.transpose(x, perm=[1, 0, 2])   # [T, B, D]
    rnn = fluid.layers.StaticRNN()
    with rnn.step():
        x_t = rnn.step_input(xt)
        h_prev = rnn.memory(shape=[-1, 16], batch_ref=x_t, init_value=0.0)
        h = fluid.layers.fc(fluid.layers.concat([x_t, h_prev], axis=1), 16,
                            act="tanh")
        rnn.update_memory(h_prev, h)
        rnn.step_output(h)
    outs = rnn()
    last = fluid.layers.slice(outs, axes=[0], starts=[5], ends=[6]) \
        if hasattr(fluid.layers, "slice") else outs
    last = fluid.layers.reshape(last, [-1, 16])
    pred = fluid.layers.fc(last, 2, act="softmax")
    loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
    fluid.optimizer.Adam(learning_rate=0.05).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    xv = rng.rand(4, 6, 8).astype(np.float32)
    yv = np.array([[0], [1], [0], [1]], np.int64)
    losses = []
    for _ in range(30):
        lv, = exe.run(feed={"x": xv, "label": yv}, fetch_list=[loss])
        losses.append(float(np.asarray(lv)))
    assert losses[-1] < 0.2, losses[-1]


def test_dynamic_lstm_forward_matches_numpy():
    hidden = 4
    seqs = [3, 5]
    total = sum(seqs)
    rng = np.random.RandomState(1)
    xproj = rng.randn(total, 4 * hidden).astype(np.float32) * 0.5
    t = fluid.create_lod_tensor(xproj, [seqs])
    x = fluid.layers.data("x", [4 * hidden], lod_level=1)
    h, c = fluid.layers.dynamic_lstm(x, size=4 * hidden,
                                     use_peepholes=False)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    got_h, = exe.run(feed={"x": t}, fetch_list=[h])

    prog = fluid.default_main_program()
    lstm_op = [o for o in prog.global_block().ops if o.type == "lstm"][0]
    w = np.asarray(fluid.global_scope().find_var(lstm_op.input("Weight")[0]))
    b = np.asarray(fluid.global_scope().find_var(lstm_op.input("Bias")[0]))

    def run_seq(xs):
        hh = np.zeros(hidden, np.float32)
        cc = np.zeros(hidden, np.float32)
        outs = []
        for xt in xs:
            g = xt + hh @ w + b[0, :4 * hidden]
            i, f, cg, o = np.split(g, 4)
            i, f, o = _sigmoid(i), _sigmoid(f), _sigmoid(o)
            cc = f * cc + i * np.tanh(cg)
            hh = o * np.tanh(cc)
            outs.append(hh.copy())
        return np.stack(outs)

    want = np.concatenate([run_seq(xproj[:3]), run_seq(xproj[3:])])
    np.testing.assert_allclose(got_h, want, rtol=1e-4, atol=1e-5)


def test_dynamic_gru_forward_matches_numpy():
    hidden = 3
    seqs = [2, 4]
    rng = np.random.RandomState(2)
    xproj = rng.randn(sum(seqs), 3 * hidden).astype(np.float32) * 0.5
    t = fluid.create_lod_tensor(xproj, [seqs])
    x = fluid.layers.data("x", [3 * hidden], lod_level=1)
    h = fluid.layers.dynamic_gru(x, size=hidden)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    got, = exe.run(feed={"x": t}, fetch_list=[h])

    prog = fluid.default_main_program()
    gru_op = [o for o in prog.global_block().ops if o.type == "gru"][0]
    w = np.asarray(fluid.global_scope().find_var(gru_op.input("Weight")[0]))
    b = np.asarray(fluid.global_scope().find_var(gru_op.input("Bias")[0]))

    def run_seq(xs):
        hh = np.zeros(hidden, np.float32)
        outs = []
        for xt in xs:
            xu, xr, xc = np.split(xt, 3)
            gh = hh @ w[:, :2 * hidden]
            u = _sigmoid(xu + gh[:hidden] + b[0, :hidden])
            r = _sigmoid(xr + gh[hidden:] + b[0, hidden:2 * hidden])
            c = np.tanh(xc + (r * hh) @ w[:, 2 * hidden:] +
                        b[0, 2 * hidden:])
            hh = (1 - u) * hh + u * c
            outs.append(hh.copy())
        return np.stack(outs)

    want = np.concatenate([run_seq(xproj[:2]), run_seq(xproj[2:])])
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_reverse_lstm_unequal_lengths_matches_numpy():
    # regression: end-padded layout means real steps are t < len in BOTH
    # scan directions; the reverse mask must not select padding
    hidden = 4
    seqs = [3, 5]
    rng = np.random.RandomState(7)
    xproj = rng.randn(sum(seqs), 4 * hidden).astype(np.float32) * 0.5
    t = fluid.create_lod_tensor(xproj, [seqs])
    x = fluid.layers.data("x", [4 * hidden], lod_level=1)
    h, c = fluid.layers.dynamic_lstm(x, size=4 * hidden,
                                     use_peepholes=False, is_reverse=True)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    got_h, = exe.run(feed={"x": t}, fetch_list=[h])
    prog = fluid.default_main_program()
    lstm_op = [o for o in prog.global_block().ops if o.type == "lstm"][0]
    w = np.asarray(fluid.global_scope().find_var(lstm_op.input("Weight")[0]))
    b = np.asarray(fluid.global_scope().find_var(lstm_op.input("Bias")[0]))

    def run_seq_rev(xs):
        hh = np.zeros(hidden, np.float32)
        cc = np.zeros(hidden, np.float32)
        outs = []
        for xt in xs[::-1]:
            g = xt + hh @ w + b[0, :4 * hidden]
            i, f, cg, o = np.split(g, 4)
            i, f, o = _sigmoid(i), _sigmoid(f), _sigmoid(o)
            cc = f * cc + i * np.tanh(cg)
            hh = o * np.tanh(cc)
            outs.append(hh.copy())
        return np.stack(outs[::-1])

    want = np.concatenate([run_seq_rev(xproj[:3]), run_seq_rev(xproj[3:])])
    assert np.abs(got_h[:3]).max() > 0, "short sequence must not be zeroed"
    np.testing.assert_allclose(got_h, want, rtol=1e-4, atol=1e-5)


def test_stacked_lstm_text_model_trains():
    # stacked_dynamic_lstm benchmark shape: embedding -> fc -> lstm -> pool
    words = fluid.layers.data("words", [1], dtype="int64", lod_level=1)
    label = fluid.layers.data("label", [1], dtype="int64")
    emb = fluid.layers.embedding(words, size=[50, 16])
    proj = fluid.layers.fc(emb, 4 * 8)
    h, c = fluid.layers.dynamic_lstm(proj, size=4 * 8, use_peepholes=False)
    pooled = fluid.layers.sequence_pool(h, "max")
    pred = fluid.layers.fc(pooled, 2, act="softmax")
    loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
    fluid.optimizer.Adam(learning_rate=0.05).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(3)
    lens = [5, 3, 6, 4]
    ids = rng.randint(0, 50, (sum(lens), 1)).astype(np.int64)
    labels = np.array([[0], [1], [0], [1]], np.int64)
    t = fluid.create_lod_tensor(ids, [lens])
    losses = []
    for _ in range(40):
        lv, = exe.run(feed={"words": t, "label": labels},
                      fetch_list=[loss])
        losses.append(float(np.asarray(lv)))
    assert losses[-1] < 0.3, losses[-1]


def test_dynamic_rnn_matches_manual_masked_scan():
    seqs = [3, 1, 2]
    rng = np.random.RandomState(4)
    flat = rng.rand(sum(seqs), 5).astype(np.float32)
    t = fluid.create_lod_tensor(flat, [seqs])
    x = fluid.layers.data("x", [5], lod_level=1)
    drnn = fluid.layers.DynamicRNN()
    with drnn.block():
        x_t = drnn.step_input(x)
        h_prev = drnn.memory(shape=[5], value=0.0)
        h = fluid.layers.scale(h_prev, 0.9) + x_t
        drnn.update_memory(h_prev, h)
        drnn.output(h)
    out = drnn()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    got, = exe.run(feed={"x": t}, fetch_list=[out])
    # manual per-sequence recurrence, flat output
    want = []
    off = 0
    for ln in seqs:
        h = np.zeros(5, np.float32)
        for i in range(ln):
            h = h * 0.9 + flat[off + i]
            want.append(h.copy())
        off += ln
    np.testing.assert_allclose(got[:sum(seqs)], np.stack(want), rtol=1e-5)


def test_while_loop_counts():
    i = fluid.layers.fill_constant([1], "int64", 0)
    limit = fluid.layers.fill_constant([1], "int64", 10)
    acc = fluid.layers.fill_constant([1], "float32", 0.0)
    cond = fluid.layers.less_than(i, limit)
    w = fluid.layers.While(cond, loop_vars=[i, acc])
    with w.block():
        new_acc = acc + 2.0
        fluid.layers.assign(new_acc, acc)
        fluid.layers.increment(i, value=1.0)
        fluid.layers.less_than(i, limit, cond=cond)
    exe = fluid.Executor(fluid.CPUPlace())
    got_i, got_acc = exe.run(feed={}, fetch_list=[i, acc])
    assert int(np.asarray(got_i)) == 10
    assert float(np.asarray(got_acc)) == 20.0


def test_ifelse_row_merge():
    x = fluid.layers.data("x", [2])
    limit = fluid.layers.fill_constant([1], "float32", 0.5)
    cond = fluid.layers.less_than(x, limit)  # broadcast compare on col 0?
    # row mask from first feature
    feat0 = fluid.layers.slice(x, axes=[1], starts=[0], ends=[1]) \
        if hasattr(fluid.layers, "slice") else x
    mask = fluid.layers.less_than(feat0, limit)
    ie = fluid.layers.IfElse(mask)
    with ie.true_block():
        xt = ie.input(x)
        ie.output(fluid.layers.scale(xt, 10.0))
    with ie.false_block():
        xf = ie.input(x)
        ie.output(fluid.layers.scale(xf, -1.0))
    out, = ie()
    exe = fluid.Executor(fluid.CPUPlace())
    xv = np.array([[0.1, 1.0], [0.9, 2.0]], np.float32)
    got, = exe.run(feed={"x": xv}, fetch_list=[out])
    np.testing.assert_allclose(got, [[1.0, 10.0], [-0.9, -2.0]], rtol=1e-6)
    del cond


def test_switch_scalar_select():
    step = fluid.layers.fill_constant([1], "float32", 7.0)
    b1 = fluid.layers.fill_constant([1], "float32", 5.0)
    lr = fluid.layers.create_global_var(shape=[1], value=0.0,
                                        dtype="float32",
                                        persistable=True, name="sw_lr")
    v_small = fluid.layers.fill_constant([1], "float32", 0.1)
    v_big = fluid.layers.fill_constant([1], "float32", 0.01)
    sw = fluid.layers.Switch()
    with sw.case(fluid.layers.less_than(step, b1)):
        sw.assign(v_small, lr)
    with sw.default():
        sw.assign(v_big, lr)
    sw.finalize()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    got, = exe.run(feed={}, fetch_list=[lr])
    assert abs(float(np.asarray(got)) - 0.01) < 1e-7


def test_stacked_lstm_propagates_maxlen_bound():
    """Regression for the round-5 32x scan-length defect: the bucketed
    @MAXLEN static bound must survive through a STACKED rnn (the first
    layer's output feeds the second's pack), or layer 2+ scans the
    whole bucketed flat total instead of ~max(lens) steps."""
    from paddle_tpu.core import registry
    from paddle_tpu.core.executor import _normalize_feeds, _lower_op

    words = fluid.layers.data("words", [1], dtype="int64", lod_level=1)
    emb = fluid.layers.embedding(words, size=[20, 8])
    proj1 = fluid.layers.fc(emb, 32)
    h1, _ = fluid.layers.dynamic_lstm(proj1, size=32, use_peepholes=False)
    proj2 = fluid.layers.fc(h1, 32)
    h2, _ = fluid.layers.dynamic_lstm(proj2, size=32, use_peepholes=False)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())

    lens = [3, 5, 2, 4]
    ids = np.random.randint(0, 20, (sum(lens), 1)).astype(np.int64)
    t = fluid.create_lod_tensor(ids, [lens])
    feed_arrays, static_info = _normalize_feeds({"words": t})
    assert static_info["words@MAXLEN"] == 8        # next pow2 of 5

    block = fluid.default_main_program().global_block()
    env = dict(feed_arrays)
    scope = fluid.global_scope()
    for n in scope.local_var_names():
        v = scope.find_var(n)
        if v is not None:
            env[n] = v
    import jax
    ctx = registry.LowerContext(env, lambda: jax.random.key(0),
                                block=block, static_info=static_info)
    for op in block.ops:
        _lower_op(ctx, op)
    # BOTH lstm outputs carry the bound; the flat env values stay
    # bucket-shaped, so without the static entry layer 2 would have
    # scanned all 16 bucketed rows
    assert static_info.get(h1.name + "@MAXLEN") == 8
    assert static_info.get(proj2.name + "@MAXLEN") == 8
    assert env[h2.name].shape[0] == feed_arrays["words"].shape[0]
