"""Device-prefetching data loader.

Reference parity: operators/reader/create_double_buffer_reader_op.cc:34,168
— a prefetch thread keeping a 2-slot device-side buffer so host→device
transfer overlaps compute. On TPU the host→device hop (through the axon
tunnel here) dominates naive per-step feeding, so this is the difference
between transfer-bound and compute-bound steps.

The prefetch path rides the core executor's feed-plan cache
(core/executor.FeedPlanCache): repeated same-shape batches skip the
per-batch normalization derivation, and feeds the caller froze
(``arr.flags.writeable = False`` — constant masks, position ids) are
committed to a device buffer ONCE and reused zero-copy every batch
instead of re-uploading.

Megastep staging (ISSUE 7): ``megabatches(k)`` generalizes the 2-slot
prefetch into a ``[k, ...]`` device-resident staging stack — k source
batches stacked on the worker thread into the layout
``Executor.run_steps(feeds=stack, k=k)`` indexes in-graph, so the host
feed of megastep N+1 overlaps device compute of megastep N.
"""

import queue
import threading

import numpy as np
import jax

__all__ = ["DeviceLoader"]


class DeviceLoader:
    """Wrap an iterable of feed dicts; yields dicts of device-resident
    jax.Arrays, transferring `capacity` batches ahead on a worker thread.

    ``plan_cache=None`` (default) builds a private feed-plan cache so
    repeated same-shape batches skip re-normalization; pass an existing
    core/executor FeedPlanCache to share plans (e.g. the consuming
    Executor's ``_feed_plans``), or ``plan_cache=False`` to disable.

    LoD feeds ride through HOST-SIDE, untouched: their flat/bucketed
    normalization carries trace-time static_info only the consuming
    executor's own pass can deliver, so the loader neither pre-splits
    nor uploads them (uploading ``np.asarray(lod_tensor)`` would
    silently strip the LoD — the pre-ISSUE-7 behavior). A batch mixing
    dense and LoD feeds still prefetches its dense values, and (since
    ISSUE 12) the dense subset rides the plan cache too — only the LoD
    values take the executor-side normalization fallback."""

    def __init__(self, feed_iterable, capacity=2, device=None,
                 sharding=None, plan_cache=None):
        self._src = feed_iterable
        self._capacity = max(1, capacity)
        self._device = device
        self._sharding = sharding
        if plan_cache is None:
            from ..core.executor import FeedPlanCache
            # commit only when placement is a single device the cache
            # can reproduce; sharded puts stay on the loader's path
            dev_fn = (lambda: self._resolve_device()) \
                if sharding is None else None
            plan_cache = FeedPlanCache(device_fn=dev_fn)
        self._plans = plan_cache or None

    def _resolve_device(self):
        """The device committed buffers land on — must agree with what
        a bare device_put would pick, or one batch could mix devices
        (jax_default_device is process-wide and e.g. serving_bench
        sets it)."""
        if self._device is not None:
            return self._device
        return jax.config.jax_default_device or jax.local_devices()[0]

    def _put(self, value):
        # explicit placement always re-puts (device_put is a no-op for
        # a value already living there), matching the pre-plan-cache
        # contract that yielded arrays honor sharding=/device=
        if self._sharding is not None:
            return jax.device_put(value, self._sharding)
        if self._device is not None:
            return jax.device_put(value, self._device)
        if isinstance(value, jax.Array):
            return value            # committed / already resident
        return jax.device_put(value)

    def _normalize(self, feed):
        """Plan-cached dense normalization on the worker thread. LoD
        feeds pass through untouched — their flat/bucketed form carries
        trace-time static_info only the executor's own normalization
        pass can deliver, so pre-splitting them here would change what
        the compiled step sees.

        A batch MIXING dense and LoD feeds (the shape a recsys scoring
        pipeline produces: ragged sparse-ID lists next to dense
        features) previously bypassed the plan cache WHOLESALE — every
        dense value re-derived its normalization per batch. Now the
        dense subset rides its own cached plan (keyed by the subset's
        signature) and only the LoD values take the documented
        executor-side fallback."""
        if self._plans is None:
            return feed
        from ..core.lod import LoDTensor
        lod = {k: v for k, v in feed.items()
               if isinstance(v, LoDTensor)}
        if not lod:
            from ..core.executor import _normalize_feeds
            arrays, _ = _normalize_feeds(feed, plan_cache=self._plans)
            return arrays
        dense = {k: v for k, v in feed.items() if k not in lod}
        if not dense:
            return feed
        from ..core.executor import _normalize_feeds
        arrays, _ = _normalize_feeds(dense, plan_cache=self._plans)
        out = dict(arrays)
        out.update(lod)
        return out

    def _stage(self, feed):
        """One prefetched batch → device (dense values) / host
        pass-through (LoD values — see the class docstring)."""
        from ..core.lod import LoDTensor
        out = {}
        for k, v in feed.items():
            if isinstance(v, LoDTensor):
                out[k] = v
            elif isinstance(v, jax.Array):
                out[k] = self._put(v)
            else:
                out[k] = self._put(np.asarray(v))
        return out

    def __iter__(self):
        q = queue.Queue(maxsize=self._capacity)
        stop = object()
        err = []

        def worker():
            try:
                for feed in self._src:
                    q.put(self._stage(self._normalize(feed)))
            except BaseException as e:   # propagate to consumer
                err.append(e)
            finally:
                q.put(stop)

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is stop:
                break
            yield item
        if err:
            raise err[0]

    def megabatches(self, k):
        """Iterate ``[k, ...]`` megastep staging stacks: k consecutive
        source batches are normalized, stacked on the WORKER thread and
        uploaded as one device-resident dict — exactly the pre-stacked
        layout ``Executor.run_steps(feeds=stack, k=k)`` (and the
        ParallelExecutor twin) index in-graph, so staging megastep N+1
        overlaps device compute of megastep N. A trailing group
        shorter than k is yielded at its true length (read k from the
        leading dim).

        LoD feeds cannot ride this path: their per-step normalization
        produces trace-time static_info (@MAXLEN, bucketing) only the
        executor's host path can derive, so a LoD batch raises a clear
        error here instead of a shape mismatch inside the scan — feed
        LoD work to ``run_steps`` as a LIST of per-step feed dicts
        instead (the documented host fallback)."""
        k = int(k)
        if k < 1:
            raise ValueError("megabatches needs k >= 1, got %d" % k)
        from ..core.lod import LoDTensor

        def stacked():
            group = []
            for feed in self._src:
                bad = sorted(n for n, v in feed.items()
                             if isinstance(v, LoDTensor))
                if bad:
                    raise ValueError(
                        "LoD feed(s) %s cannot ride the [k, ...] "
                        "megastep staging stack (their normalization "
                        "needs the executor's trace-time static_info); "
                        "pass run_steps a LIST of per-step feed dicts "
                        "instead" % bad)
                group.append(self._normalize(feed))
                if len(group) == k:
                    yield self._stack(group)
                    group = []
            if group:
                yield self._stack(group)

        for staged in DeviceLoader(stacked(), capacity=self._capacity,
                                   device=self._device,
                                   sharding=self._stack_sharding(),
                                   plan_cache=False):
            yield staged

    def _stack_sharding(self):
        """The loader's per-batch sharding spec remapped to the
        ``[k, ...]`` stack layout: dim 0 is the scan dim (never
        sharded), every batch dim shifts right by one — so a loader
        built with ``P('dp')`` stages stacks as ``P(None, 'dp')``,
        exactly what ``ParallelExecutor.run_steps`` expects. Passing
        the per-batch spec through unchanged would shard the SCAN dim
        (crashing when k is not divisible by the mesh axis, silently
        mis-laying the stack when it is)."""
        s = self._sharding
        if s is None:
            return None
        from jax.sharding import NamedSharding, PartitionSpec
        if isinstance(s, NamedSharding):
            return NamedSharding(s.mesh, PartitionSpec(None, *s.spec))
        raise ValueError(
            "megabatches cannot remap sharding type %s to the "
            "[k, ...] stack layout — pass a NamedSharding (its spec "
            "gains a leading None for the scan dim) or build the "
            "loader without sharding=" % type(s).__name__)

    @staticmethod
    def _stack(group):
        names = sorted(group[0])
        for i, g in enumerate(group[1:], 1):
            if sorted(g) != names:
                raise ValueError(
                    "megabatch group mixes feed names: batch %d has %s,"
                    " batch 0 has %s" % (i, sorted(g), names))
        return {n: np.stack([np.asarray(g[n]) for g in group])
                for n in names}


def repeat_feed(feed, n):
    """Iterator yielding the same feed dict n times (benchmark helper)."""
    for _ in range(n):
        yield feed
