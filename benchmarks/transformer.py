"""Transformer LM benchmark (north star: tokens/sec/chip)."""

import numpy as np

from common import parse_args, get_place, time_loop  # noqa: E402

import paddle_tpu as fluid  # noqa: E402
from paddle_tpu.models import transformer as T  # noqa: E402


def main():
    args = parse_args(
        "transformer", batch_size=16, iterations=30,
        extra=lambda p: (
            p.add_argument("--max_len", type=int, default=256),
            p.add_argument("--n_layer", type=int, default=4),
            p.add_argument("--n_head", type=int, default=8),
            p.add_argument("--d_model", type=int, default=512),
            p.add_argument("--d_inner", type=int, default=2048),
            p.add_argument("--vocab", type=int, default=8192),
            p.add_argument("--packed", type=int, default=1,
                           help="full-length packed sequences (flash "
                                "attention fused path)")))
    avg_cost, _ = T.transformer_lm(
        vocab_size=args.vocab, max_len=args.max_len, n_layer=args.n_layer,
        n_head=args.n_head, d_model=args.d_model, d_inner=args.d_inner,
        packed=bool(args.packed))
    fluid.optimizer.Adam(learning_rate=1e-4).minimize(avg_cost)
    if args.dtype == "bfloat16":
        fluid.amp.enable_amp()
    exe = fluid.Executor(get_place(args))
    exe.run(fluid.default_startup_program())

    rng = np.random.RandomState(0)
    feeds = T.make_lm_batch(rng, args.batch_size, args.max_len, args.vocab)
    if args.packed:
        feeds["mask"] = np.ones_like(feeds["mask"])
    tokens_per_batch = int(feeds["mask"].sum())
    # analytic train FLOPs/token (3x fwd): per layer 8d^2 (qkvo) +
    # 4*d*d_inner (ffn) + 4*T*d (attention); head 2*d*V
    d, t = args.d_model, args.max_len
    flops_tok = 3 * (args.n_layer * (8 * d * d + 4 * d * args.d_inner
                                     + 4 * t * d) + 2 * d * args.vocab)
    import os
    windows = max(1, int(os.environ.get("PADDLE_TPU_BENCH_WINDOWS", "1")))
    total = args.iterations * windows + args.skip_batch_num
    loader = iter(fluid.reader.DeviceLoader(
        fluid.reader.repeat_feed(feeds, total + 1)))

    last = []

    def step(i):
        loss, = exe.run(feed=next(loader), fetch_list=[avg_cost],
                        return_numpy=False)
        last[:] = [loss]

    def sync():
        print("loss %.4f" % float(np.asarray(last[0])))

    tps = time_loop(step, args, tokens_per_batch, "tokens", sync=sync)
    import sys
    print("MFU %.1f%% (%.0f tok/s x %.1f MFLOP/tok / 197 TFLOP/s peak)"
          % (tps * flops_tok / 197e12 * 100, tps, flops_tok / 1e6),
          file=sys.stderr)
    return tps


if __name__ == "__main__":
    main()
