"""NTP-style clock-offset estimation over RPC round trips.

Two processes' span logs carry ``time.time()`` timestamps from two
unsynchronized clocks; nesting a server span inside its client span in
the merged timeline needs the relative offset. The midpoint method:
the client stamps t0 (just before send) and t3 (just after the reply),
the server stamps its own clock ``ts`` while handling the probe
(the CLKS verb, distributed/rpc.py). Assuming symmetric network legs,
the server handled the probe at client-clock (t0+t3)/2, so

    offset = ts - (t0 + t3) / 2        (server clock minus client's)

with uncertainty bounded by half the round-trip time. Clients sample
periodically per peer (Tracer.clock_due) and record each sample as a
``clock`` row; the merge picks the minimum-RTT sample per edge (the
tightest bound) and chains offsets across processes that never talked
directly.
"""

import time

__all__ = ["midpoint_offset", "probe"]


def midpoint_offset(t0, server_t, t3):
    """(offset, rtt) from one probe's three timestamps (midpoint
    method). ``offset`` is the server clock minus the client clock."""
    return server_t - (t0 + t3) / 2.0, t3 - t0


def probe(trc, peer, exchange):
    """One rate-limited probe against ``peer``: ``exchange()`` performs
    the CLKS round trip on an IDLE client connection and returns the
    server's epoch seconds (or None on a non-OK reply). Records a
    ``clock`` row; returns the offset or None. Socket errors propagate
    — the caller owns the connection and must drop it (a half-done
    probe leaves the stream desynced)."""
    if not trc.clock_due(peer):
        return None
    t0 = time.time()
    server_t = exchange()
    t3 = time.time()
    if server_t is None:
        return None
    offset, rtt = midpoint_offset(t0, float(server_t), t3)
    trc.record_clock(peer, offset, rtt)
    return offset
