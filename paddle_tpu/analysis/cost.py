"""Static per-eqn cost model: FLOPs + bytes from avals alone.

The roll-up the reference framework never had at the IR level (its cost
model lived in per-op C++ GetExpectedKernelType heuristics); here every
jaxpr eqn gets a (flops, bytes) estimate so rules can rank diagnostics
by how much compute sits behind them. Matmul FLOPs come from
ops/matmul_stats.dot_general_flops — the same accounting the fused
conv+BN kernel uses for its perf claims.
"""

import numpy as np

from ..ops.matmul_stats import dot_general_flops
from .engine import sub_jaxprs, aval_nbytes as _aval_bytes

# eqns that are pure data movement / metadata: zero FLOPs, bytes only
_MOVEMENT = {
    "broadcast_in_dim", "reshape", "transpose", "squeeze", "rev",
    "expand_dims", "slice", "concatenate", "pad", "copy",
    "convert_element_type", "bitcast_convert_type", "stop_gradient",
    "gather", "scatter", "dynamic_slice", "dynamic_update_slice",
    "device_put", "iota", "select_n",
}

# expensive transcendentals: count a few FLOPs per element
_TRANSCENDENTAL = {"exp", "log", "log1p", "tanh", "logistic", "erf",
                   "rsqrt", "sqrt", "pow", "sin", "cos", "cbrt",
                   "exp2", "expm1"}


def _aval_size(aval):
    try:
        return float(np.prod(aval.shape, dtype=np.float64))
    except Exception:
        return 0.0


def has_subjaxpr(eqn):
    """True for call-like eqns (scan/while/cond/pjit/shard_map...) whose
    cost lives in their inner jaxpr — counted there, not on the eqn."""
    for _ in sub_jaxprs(eqn):
        return True
    return False


def eqn_cost(eqn):
    """(flops, bytes) estimate for one eqn. Bytes = operands + outputs
    touched once (the bandwidth floor); FLOPs from shapes."""
    prim = eqn.primitive.name
    if has_subjaxpr(eqn):
        return 0.0, 0.0
    nbytes = sum(_aval_bytes(v.aval) for v in eqn.invars
                 if hasattr(v, "aval"))
    nbytes += sum(_aval_bytes(v.aval) for v in eqn.outvars
                  if hasattr(v, "aval"))
    if prim == "dot_general":
        lhs, rhs = eqn.invars[0].aval, eqn.invars[1].aval
        flops = dot_general_flops(lhs.shape, rhs.shape,
                                  eqn.params["dimension_numbers"])
        return flops, nbytes
    if prim == "conv_general_dilated":
        rhs = eqn.invars[1].aval
        out = eqn.outvars[0].aval
        # out elements x (2 * K_spatial * Cin/groups) MACs each
        dn = eqn.params["dimension_numbers"]
        k_spatial = 1.0
        for i in dn.rhs_spec[2:]:
            k_spatial *= rhs.shape[i]
        cin = rhs.shape[dn.rhs_spec[1]]  # already Cin/groups
        flops = 2.0 * _aval_size(out) * k_spatial * cin
        return flops, nbytes
    if prim in _MOVEMENT:
        return 0.0, nbytes
    out_sz = max([_aval_size(v.aval) for v in eqn.outvars
                  if hasattr(v, "aval")] or [0.0])
    in_sz = max([_aval_size(v.aval) for v in eqn.invars
                 if hasattr(v, "aval")] or [0.0])
    if prim.startswith("reduce_") or prim in ("argmax", "argmin",
                                              "cumsum", "cumlogsumexp",
                                              "cummax", "cumprod"):
        return in_sz, nbytes
    if prim in _TRANSCENDENTAL:
        return 8.0 * out_sz, nbytes
    if prim == "sort":
        n = max(in_sz, 1.0)
        return n * np.log2(max(n, 2.0)), nbytes
    # default: one FLOP per output element (elementwise / compare / etc.)
    return out_sz, nbytes


class CostTable:
    """Per-eqn costs over an Analysis, weighted by loop trip counts
    (a scan body's cost counts ``length`` times)."""

    def __init__(self, analysis):
        self.per_eqn = {}     # id(eqn) -> (flops, bytes, weight)
        self.total_flops = 0.0
        self.total_bytes = 0.0
        for view, eqn in analysis.iter_eqns():
            f, b = eqn_cost(eqn)
            w = view.weight
            self.per_eqn[id(eqn)] = (f, b, w)
            self.total_flops += f * w
            self.total_bytes += b * w

    def flops(self, eqn):
        f, _, w = self.per_eqn.get(id(eqn), (0.0, 0.0, 1.0))
        return f * w

    def bytes(self, eqn):
        _, b, w = self.per_eqn.get(id(eqn), (0.0, 0.0, 1.0))
        return b * w


def step_costs(fn, example_args):
    """(total_flops, total_bytes) of one call of ``fn(*example_args)``
    from the static cost model — abstract trace only, nothing executes.
    This is the bridge paddle_tpu.monitor uses to price a compiled step
    once per compile and derive per-step MFU from wall time."""
    from .engine import Analysis
    table = CostTable(Analysis(fn, example_args, name="step"))
    return table.total_flops, table.total_bytes


def fmt_flops(f):
    for unit, scale in (("TFLOP", 1e12), ("GFLOP", 1e9), ("MFLOP", 1e6),
                        ("kFLOP", 1e3)):
        if f >= scale:
            return "%.2f %s" % (f / scale, unit)
    return "%.0f FLOP" % f


def fmt_bytes(b):
    for unit, scale in (("GiB", 2 ** 30), ("MiB", 2 ** 20),
                        ("KiB", 2 ** 10)):
        if b >= scale:
            return "%.2f %s" % (b / scale, unit)
    return "%.0f B" % b
