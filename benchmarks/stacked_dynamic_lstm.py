"""Stacked dynamic LSTM benchmark — parity with reference
benchmark/fluid/stacked_dynamic_lstm.py (LSTM text classification;
reference baseline: 184 ms/batch @ h=512 bs=64 on K40m)."""

import numpy as np

from common import parse_args, get_place, time_loop  # noqa: E402

import paddle_tpu as fluid  # noqa: E402


def build(vocab, hidden, stacked, classes=2):
    words = fluid.layers.data("words", [1], dtype="int64", lod_level=1)
    label = fluid.layers.data("label", [1], dtype="int64")
    x = fluid.layers.embedding(words, size=[vocab, hidden])
    for _ in range(stacked):
        proj = fluid.layers.fc(x, 4 * hidden)
        h, c = fluid.layers.dynamic_lstm(proj, size=4 * hidden,
                                         use_peepholes=False)
        x = h
    pooled = fluid.layers.sequence_pool(x, "max")
    pred = fluid.layers.fc(pooled, classes, act="softmax")
    loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
    fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)
    return words, label, loss


def main():
    args = parse_args(
        "stacked_dynamic_lstm", batch_size=64, iterations=20,
        extra=lambda p: (
            p.add_argument("--hidden_dim", type=int, default=512),
            p.add_argument("--stacked_num", type=int, default=3),
            p.add_argument("--seq_len", type=int, default=80),
            p.add_argument("--vocab", type=int, default=5000)))
    words, label, loss = build(args.vocab, args.hidden_dim,
                               args.stacked_num)
    exe = fluid.Executor(get_place(args))
    exe.run(fluid.default_startup_program())

    rng = np.random.RandomState(0)
    lens = rng.randint(args.seq_len // 2, args.seq_len + 1,
                       size=args.batch_size).tolist()
    ids = rng.randint(0, args.vocab, (sum(lens), 1)).astype(np.int64)
    t = fluid.create_lod_tensor(ids, [lens])
    ys = rng.randint(0, 2, (args.batch_size, 1)).astype(np.int64)

    last = []

    def step(i):
        lv, = exe.run(feed={"words": t, "label": ys}, fetch_list=[loss],
                      return_numpy=False)
        last[:] = [lv]

    def sync():
        # one blocking fetch per timing window (not per step: the
        # sandbox tunnel charges ~90ms per sync)
        if last:
            print("loss %.4f" % float(np.asarray(last[0])))

    tps = time_loop(step, args, sum(lens), "tokens", sync=sync)
    # the reference anchor is ms/BATCH (benchmark/README.md:108-117,
    # 184 ms/batch at h=512 bs=64) — report in its unit
    ms_per_batch = 1000.0 * sum(lens) / tps
    print("=> %.1f ms/batch (reference K40m anchor: 184 ms/batch)"
          % ms_per_batch)
    return ms_per_batch


if __name__ == "__main__":
    main()
