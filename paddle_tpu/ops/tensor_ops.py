"""Tensor manipulation + creation ops.

Reference parity: operators/{fill_constant,fill_zeros_like,assign,cast,concat,
split,reshape,transpose,expand,gather,scatter,one_hot,uniform_random,
gaussian_random,lookup_table,pad,increment,multiplex,label_smooth,
assign_value,shape,slice,is_empty}_op.cc.

Random ops consume a fresh PRNG key from the trace context (functional
randomness — the TPU-native replacement for the reference's cuRAND states).
"""

import numpy as np
import jax
import jax.numpy as jnp

from ..core.program import convert_dtype, runtime_dtype
from .common import I64
from ..core.registry import register


def _np_dtype(d):
    return jnp.dtype(runtime_dtype(d))


@register("fill_constant", stateful_rng=False)
def _fill_constant(ctx, op):
    shape = op.attr("shape", [1])
    dtype = _np_dtype(op.attr("dtype", "float32"))
    value = op.attr("value", 0.0)
    ctx.set_out(op, "Out", jnp.full(tuple(shape), value, dtype=dtype))


@register("fill_constant_batch_size_like")
def _fill_cbsl(ctx, op):
    ref = ctx.in1(op, "Input")
    shape = list(op.attr("shape"))
    in_idx = op.attr("input_dim_idx", 0)
    out_idx = op.attr("output_dim_idx", 0)
    shape[out_idx] = ref.shape[in_idx]
    dtype = _np_dtype(op.attr("dtype", "float32"))
    ctx.set_out(op, "Out",
                jnp.full(tuple(shape), op.attr("value", 0.0), dtype=dtype))


@register("fill_zeros_like")
def _fill_zeros_like(ctx, op):
    ctx.set_out(op, "Out", jnp.zeros_like(ctx.in1(op, "X")))


@register("fill_any_like")
def _fill_any_like(ctx, op):
    x = ctx.in1(op, "X")
    ctx.set_out(op, "Out", jnp.full_like(x, op.attr("value", 0.0)))


@register("assign")
def _assign(ctx, op):
    ctx.set_out(op, "Out", ctx.in1(op, "X"))


@register("assign_value")
def _assign_value(ctx, op):
    shape = op.attr("shape")
    dtype = _np_dtype(op.attr("dtype", "float32"))
    values = op.attr("values")
    if isinstance(values, np.ndarray):
        arr = values.astype(dtype)
    else:
        arr = np.array(values, dtype=dtype)
    ctx.set_out(op, "Out", jnp.asarray(arr.reshape(shape)))


@register("cast")
def _cast(ctx, op):
    x = ctx.in1(op, "X")
    ctx.set_out(op, "Out", x.astype(_np_dtype(op.attr("out_dtype"))))


@register("concat")
def _concat(ctx, op):
    xs = ctx.in_list(op, "X")
    ctx.set_out(op, "Out", jnp.concatenate(xs, axis=op.attr("axis", 0)))


@register("split")
def _split(ctx, op):
    x = ctx.in1(op, "X")
    axis = op.attr("axis", 0)
    sections = op.attr("sections")
    num = op.attr("num", 0)
    if sections:
        idx = np.cumsum(sections)[:-1].tolist()
        outs = jnp.split(x, idx, axis=axis)
    else:
        outs = jnp.split(x, num, axis=axis)
    for name, val in zip(op.output("Out"), outs):
        ctx.env[name] = val


@register("reshape")
@register("reshape2")
def _reshape(ctx, op):
    x = ctx.in1(op, "X")
    shape = list(op.attr("shape"))
    # reference: 0 means copy input dim at that position
    for i, s in enumerate(shape):
        if s == 0:
            shape[i] = x.shape[i]
    ctx.set_out(op, "Out", x.reshape(shape))


@register("squeeze")
def _squeeze(ctx, op):
    x = ctx.in1(op, "X")
    axes = op.attr("axes", [])
    if axes:
        ctx.set_out(op, "Out", jnp.squeeze(x, axis=tuple(axes)))
    else:
        ctx.set_out(op, "Out", jnp.squeeze(x))


@register("unsqueeze")
def _unsqueeze(ctx, op):
    x = ctx.in1(op, "X")
    for a in sorted(op.attr("axes", [])):
        x = jnp.expand_dims(x, a)
    ctx.set_out(op, "Out", x)


@register("transpose")
@register("transpose2")
def _transpose(ctx, op):
    x = ctx.in1(op, "X")
    ctx.set_out(op, "Out", jnp.transpose(x, axes=op.attr("axis")))


@register("expand")
def _expand(ctx, op):
    x = ctx.in1(op, "X")
    times = op.attr("expand_times")
    ctx.set_out(op, "Out", jnp.tile(x, tuple(times)))


@register("stack")
def _stack(ctx, op):
    xs = ctx.in_list(op, "X")
    ctx.set_out(op, "Y", jnp.stack(xs, axis=op.attr("axis", 0)))


@register("unstack")
def _unstack(ctx, op):
    x = ctx.in1(op, "X")
    axis = op.attr("axis", 0)
    outs = [jnp.squeeze(s, axis) for s in jnp.split(x, x.shape[axis], axis)]
    for name, val in zip(op.output("Y"), outs):
        ctx.env[name] = val


@register("gather")
def _gather(ctx, op):
    x = ctx.in1(op, "X")
    idx = ctx.in1(op, "Index")
    ctx.set_out(op, "Out", jnp.take(x, idx.astype(jnp.int32), axis=0))


@register("scatter")
def _scatter(ctx, op):
    x = ctx.in1(op, "X")
    idx = ctx.in1(op, "Ids").astype(jnp.int32)
    upd = ctx.in1(op, "Updates")
    if op.attr("overwrite", True):
        out = x.at[idx].set(upd)
    else:
        out = x.at[idx].add(upd)
    ctx.set_out(op, "Out", out)


@register("one_hot")
def _one_hot(ctx, op):
    x = ctx.in1(op, "X")
    depth = op.attr("depth")
    x = x.reshape(x.shape[:-1]) if x.shape and x.shape[-1] == 1 else x
    ctx.set_out(op, "Out", jax.nn.one_hot(x.astype(jnp.int32), depth))


# Random ops are stateful_rng: each draw advances the trace-order PRNG
# stream, so the transform tier must pin them in place (removing or
# deduplicating one would shift every later op's stream position).
@register("uniform_random", stateful_rng=True)
@register("uniform_random_batch_size_like", stateful_rng=True)
def _uniform_random(ctx, op):
    shape = list(op.attr("shape"))
    ref = ctx.maybe_get(op.input("Input")[0]) if op.input("Input") else None
    if ref is not None:
        shape[op.attr("output_dim_idx", 0)] = ref.shape[op.attr("input_dim_idx", 0)]
    dtype = _np_dtype(op.attr("dtype", "float32"))
    lo = op.attr("min", -1.0)
    hi = op.attr("max", 1.0)
    out = jax.random.uniform(ctx.rng(), tuple(shape), dtype=jnp.float32,
                             minval=lo, maxval=hi).astype(dtype)
    ctx.set_out(op, "Out", out)


@register("gaussian_random", stateful_rng=True)
@register("gaussian_random_batch_size_like", stateful_rng=True)
def _gaussian_random(ctx, op):
    shape = list(op.attr("shape"))
    ref = ctx.maybe_get(op.input("Input")[0]) if op.input("Input") else None
    if ref is not None:
        shape[op.attr("output_dim_idx", 0)] = ref.shape[op.attr("input_dim_idx", 0)]
    dtype = _np_dtype(op.attr("dtype", "float32"))
    mean = op.attr("mean", 0.0)
    std = op.attr("std", 1.0)
    out = mean + std * jax.random.normal(ctx.rng(), tuple(shape),
                                         dtype=jnp.float32)
    ctx.set_out(op, "Out", out.astype(dtype))


@register("truncated_gaussian_random", stateful_rng=True)
def _truncated_gaussian_random(ctx, op):
    shape = tuple(op.attr("shape"))
    dtype = _np_dtype(op.attr("dtype", "float32"))
    mean = op.attr("mean", 0.0)
    std = op.attr("std", 1.0)
    out = mean + std * jax.random.truncated_normal(
        ctx.rng(), -2.0, 2.0, shape, dtype=jnp.float32)
    ctx.set_out(op, "Out", out.astype(dtype))


@register("lookup_table")
def _lookup_table(ctx, op):
    """Embedding lookup (operators/lookup_table_op.cc). ids may have a
    trailing 1 dim (reference convention). padding_idx rows read as zero."""
    w = ctx.in1(op, "W")
    ids = ctx.in1(op, "Ids").astype(jnp.int32)
    if ids.ndim >= 2 and ids.shape[-1] == 1:
        ids = ids.reshape(ids.shape[:-1])
    padding_idx = op.attr("padding_idx", -1)
    out = jnp.take(w, jnp.clip(ids, 0, w.shape[0] - 1), axis=0)
    if padding_idx is not None and padding_idx >= 0:
        mask = (ids != padding_idx)[..., None]
        out = out * mask.astype(out.dtype)
    ctx.set_out(op, "Out", out)


@register("pad")
def _pad(ctx, op):
    x = ctx.in1(op, "X")
    paddings = op.attr("paddings")  # flat [before0, after0, before1, ...]
    pads = [(paddings[2 * i], paddings[2 * i + 1]) for i in range(x.ndim)]
    ctx.set_out(op, "Out", jnp.pad(x, pads,
                                   constant_values=op.attr("pad_value", 0.0)))


@register("pad_constant_like")
def _pad_constant_like(ctx, op):
    x = ctx.in1(op, "X")    # big
    y = ctx.in1(op, "Y")    # small
    pads = [(0, xs - ys) for xs, ys in zip(x.shape, y.shape)]
    ctx.set_out(op, "Out", jnp.pad(y, pads,
                                   constant_values=op.attr("pad_value", 0.0)))


@register("crop")
def _crop(ctx, op):
    x = ctx.in1(op, "X")
    offsets = op.attr("offsets")
    shape = op.attr("shape")
    slices = tuple(slice(o, o + s) for o, s in zip(offsets, shape))
    ctx.set_out(op, "Out", x[slices])


@register("slice")
def _slice(ctx, op):
    x = ctx.in1(op, "Input")
    axes = op.attr("axes")
    starts = op.attr("starts")
    ends = op.attr("ends")
    slices = [slice(None)] * x.ndim
    for a, s, e in zip(axes, starts, ends):
        slices[a] = slice(s, e)
    ctx.set_out(op, "Out", x[tuple(slices)])


@register("shape")
def _shape(ctx, op):
    x = ctx.in1(op, "Input")
    ctx.set_out(op, "Out", jnp.asarray(x.shape, dtype=I64()))


@register("increment")
def _increment(ctx, op):
    x = ctx.in1(op, "X")
    # keep x's dtype: int counters must stay int (a python-float step would
    # silently promote and break while-loop carry types)
    ctx.set_out(op, "Out", x + jnp.asarray(op.attr("step", 1.0), x.dtype))


@register("multiplex")
def _multiplex(ctx, op):
    ids = ctx.in1(op, "Ids").astype(jnp.int32).reshape(-1)
    xs = jnp.stack(ctx.in_list(op, "X"), axis=0)   # [K, B, ...]
    ctx.set_out(op, "Out", xs[ids, jnp.arange(xs.shape[1])])


@register("label_smooth")
def _label_smooth(ctx, op):
    x = ctx.in1(op, "X")
    eps = op.attr("epsilon", 0.0)
    dist = ctx.in1(op, "PriorDist")
    k = x.shape[-1]
    if dist is not None:
        out = (1 - eps) * x + eps * dist
    else:
        out = (1 - eps) * x + eps / k
    ctx.set_out(op, "Out", out)


@register("is_empty")
def _is_empty(ctx, op):
    x = ctx.in1(op, "X")
    ctx.set_out(op, "Out", jnp.asarray(x.size == 0))


@register("range")
def _range(ctx, op):
    start = ctx.in1(op, "Start")
    end = ctx.in1(op, "End")
    step = ctx.in1(op, "Step")
    try:
        ctx.set_out(op, "Out",
                    jnp.arange(float(start), float(end), float(step)))
    except jax.errors.TracerArrayConversionError:
        raise NotImplementedError(
            "range op requires static Start/End/Step (constants), got "
            "traced values — XLA needs static output shapes")


@register("linspace")
def _linspace(ctx, op):
    start = op.attr("start")
    stop = op.attr("stop")
    num = op.attr("num")
    ctx.set_out(op, "Out", jnp.linspace(start, stop, num))


@register("sequence_mask")
def _sequence_mask(ctx, op):
    x = ctx.in1(op, "X")
    maxlen = op.attr("maxlen", -1)
    if maxlen is None or maxlen < 0:
        maxlen = op.attr("static_maxlen")
    dtype = _np_dtype(op.attr("out_dtype", "float32"))
    mask = (jnp.arange(maxlen)[None, :] < x.reshape(-1, 1)).astype(dtype)
    ctx.set_out(op, "Y", mask.reshape(tuple(x.shape) + (maxlen,)))


@register("delete_var")
def _delete_var(ctx, op):
    for n in op.input("X"):
        ctx.env.pop(n, None)


@register("print")
def _print(ctx, op):
    x = ctx.in1(op, "In")
    jax.debug.print(op.attr("message", "") + " {}", x)
    ctx.set_out(op, "Out", x)
