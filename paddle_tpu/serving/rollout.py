"""Verdict-gated model rollouts (ISSUE 19): the canary analysis plane's
controller — artifact in, promoted-or-rolled-back fleet out.

The pipeline composes seams that already exist instead of inventing a
parallel one:

  boot        spawn ``candidates`` fleet.Replica cells from the NEW
              artifact under role ``candidate`` (the router keys them
              at an offset so the journal / dedup / lease-expiry
              machinery covers them wholesale),
  shadow      ``Router.arm_shadow``: a sampled fraction of live decode
              requests is DUPLICATED to candidates — scored, never
              served, never counted in the incumbent's SLO histograms
              (the PR-6 exclusion discipline); candidate and incumbent
              results join by rid into ``mirror_pair`` recorder rows,
  verdict     a ``signals.DeltaRule`` buffers the mirrored window's
              rows and decides EXACTLY ONCE via ``slo.evaluate_delta``
              — candidate-vs-incumbent percentile inflation, error-
              rate delta, token agreement — once ``min_pairs`` joined
              pairs and ``min_requests`` per side have landed. FAIL
              fires through the normal Signals edge: offender traces
              retained, forensics bundle captured, incident row landed,
  canary      PASS advances to ``Router.arm_canary``: a small weighted
              fraction is served FOR REAL by candidates (version
              stamped on row / span / lease) and a second DeltaRule —
              token agreement dropped, there are no mirrored pairs to
              join — gates on the real-traffic deltas,
  rolling     PASS promotes via the ``Autoscaler``'s existing rolling
              weight update (boot v2 -> health gate -> drain v1 ->
              repeat), which already carries the exactly-once contract
              and its own chaos gates,
  rollback    ANY FAIL (including a forced one) disarms the mirror
              FIRST — so a rollout aborted in shadow serves ZERO
              candidate-only tokens — then retires every candidate
              cell and returns the fleet to single-version routing.
              Unfinished CANARY requests resubmit to incumbents via
              the journal: exactly-once completion holds through the
              rollback.

Chaos surfaces: the armed fault plan's kill targets ``shadow`` (value
= joined mirror pairs) and ``canary`` (value = canary-served requests)
crash one live candidate cell MID-phase; the controller reconciles —
bounded respawns from the same artifact — so the verdict still lands.
``tests/test_rollout.py`` gates the whole pipeline under seeded frame
faults + mid-phase kills on token-identical exactly-once completion
with zero shed.

The controller is a fleet citizen per the PR-17 forensics contract:
``RolloutServer`` answers METR / HLTH / DUMP / CLKS / EXIT plus the
rollout-specific idempotent VERD (current phase + per-phase verdicts)
on the shared frame protocol, and lease-registers under role
``rollout`` so collectors and the ``monitor bundle`` coordinator
discover it without configuration.
"""

import json
import threading
import time

from ..distributed import membership as _membership
from ..distributed.membership import KVClient
from ..distributed.rpc import (_send_msg, _recv_msg, _clock_reply,
                               _metr_reply, _hlth_reply, _dump_reply)
from ..monitor import runtime as _monrt
from ..monitor import signals as _signals
from ..monitor.collector import ROLLOUT_ROLE
from ..resilience import faults as _faults
from ..trace import runtime as _trace
from .fleet import CANDIDATE_ROLE, Replica

__all__ = ["RolloutController", "RolloutServer", "ROLLOUT_ROLE",
           "fetch_verdicts"]


class RolloutServer:
    """Scrape + black-box + verdict endpoint of the rollout controller
    (METR / HLTH / DUMP / CLKS / VERD / EXIT on the shared frame
    protocol — all idempotent reads plus the admin EXIT). ``DUMP``
    replies via ``rpc._dump_reply`` with the controller's live state;
    ``VERD`` replies with the phase + per-phase verdict dict (a read
    of decided state: safe to re-issue, hence its ``idempotent``
    class in ``resilience.retry.VERB_CLASSES``)."""

    def __init__(self, state_fn, verdict_fn, host="127.0.0.1",
                 port=0):
        import socketserver
        self._state_fn = state_fn
        self._verdict_fn = verdict_fn
        outer = self

        def _serve(request, op, payload):
            if op == "METR":
                _metr_reply(request, payload, role=ROLLOUT_ROLE)
            elif op == "HLTH":
                _hlth_reply(request, role=ROLLOUT_ROLE)
            elif op == "DUMP":
                try:
                    state = outer._state_fn()
                except Exception as e:       # capture must not die
                    state = {"error": repr(e)}
                _dump_reply(request, payload, role=ROLLOUT_ROLE,
                            state=state)
            elif op == "VERD":
                try:
                    v = outer._verdict_fn()
                except Exception as e:
                    v = {"error": repr(e)}
                _send_msg(request, "VAL", "",
                          json.dumps(v, default=repr).encode())
            elif op == "CLKS":
                _clock_reply(request)
            elif op == "EXIT":
                _send_msg(request, "OK")
                outer.stop()
                return False
            else:
                _send_msg(request, "ERR", "unknown op %s" % op)
            return True

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                # same trace-header discipline as every dispatch loop:
                # a traced scrape nests under the caller's client span
                try:
                    while True:
                        op, name, payload, tctx = _recv_msg(
                            self.request, want_ctx=True)
                        trc = _trace._TRACER
                        if trc is not None and tctx is not None \
                                and op != "CLKS":
                            with trc.server_span("rollout." + op,
                                                 tctx, op=op):
                                cont = _serve(self.request, op,
                                              payload)
                        else:
                            cont = _serve(self.request, op, payload)
                        if not cont:
                            break
                except (ConnectionError, OSError):
                    pass

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.port = self._server.server_address[1]
        self.endpoint = "%s:%d" % (host, self.port)
        trc = _trace._TRACER
        if trc is not None:
            trc.record_server_port(self.port, self.endpoint)
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="ptpu-rollout-ctl")

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        if self._thread.is_alive():
            self._server.shutdown()
        self._server.server_close()


def fetch_verdicts(endpoint, timeout=2.0):
    """One VERD round trip: the controller's phase + per-phase verdict
    dict as served on the wire (tests and dashboards share it)."""
    import socket
    host, port = endpoint.rsplit(":", 1)
    sock = socket.create_connection((host, int(port)),
                                    timeout=timeout)
    try:
        sock.settimeout(timeout)
        _send_msg(sock, "VERD")
        op, _, payload = _recv_msg(sock)
        if op != "VAL":
            raise ConnectionError("VERD reply %s" % op)
        return json.loads(bytes(payload).decode())
    finally:
        sock.close()


class RolloutController:
    """One verdict-gated rollout attempt: artifact directory in,
    ``run()`` drives boot -> shadow -> canary -> rolling to a terminal
    ``promoted`` or ``rolled-back`` phase. Synchronous by design — the
    state machine IS the call stack, and every transition lands a
    ``rollout`` flight-recorder row — with an internal wait loop that
    feeds the delta evaluator from the armed flight recorder, consults
    the chaos plan, and reconciles killed candidates while a verdict
    is pending.

    ``spec`` is an SLO spec dict carrying a ``"delta"`` block (or the
    delta block itself). The flight recorder must be armed
    (``monitor.session`` / ``--flag monitor_record``): the mirrored
    window's evidence comes from recorder rows, same rows ``monitor
    watch`` and the batch CLI read — no parallel plumbing."""

    def __init__(self, kv_endpoint, router, autoscaler, artifact,
                 spec, version=None, candidates=1,
                 shadow_fraction=None, canary_weight=None,
                 verdict_timeout=60.0, max_respawns=2,
                 cand_slot_span=4, slots=2, ttl=0.5, register=True,
                 control_slots=4, capture=False, capture_dir=None,
                 **engine_kwargs):
        if version is None and isinstance(artifact, str):
            import os
            version = os.path.basename(os.path.normpath(artifact))
        delta = spec.get("delta", spec) if isinstance(spec, dict) \
            else spec
        from .. import slo as _slo
        self.delta = _slo.validate_delta_spec(dict(delta))
        self.router = router
        self.autoscaler = autoscaler
        self.artifact = artifact
        self.version = str(version)
        self.candidates = int(candidates)
        self.shadow_fraction = shadow_fraction
        self.canary_weight = canary_weight
        self.verdict_timeout = float(verdict_timeout)
        self.max_respawns = int(max_respawns)
        self._cand_span = int(cand_slot_span)
        self._slots = int(slots)
        self._ttl = float(ttl)
        self._engine_kwargs = dict(engine_kwargs)
        self._capture = bool(capture)
        self._capture_dir = capture_dir
        self._kv_endpoint = kv_endpoint
        self._kv = KVClient(kv_endpoint)
        self._lock = threading.Lock()
        self.cells = []            # every incarnation (test teardown)
        self._cands = []           # live candidate cells
        self.phase = "idle"
        self.verdicts = {}         # phase -> verdict report dict
        self.respawns = 0
        self.kills = 0             # chaos kills this controller issued
        self.reason = None         # terminal detail (promoted too)
        self.convergence_s = None
        self._forced = None        # ("FAIL", reason) override
        self._cursor = None        # recorder cursor (evidence feed)
        self._t0 = None
        # PR-17 forensics contract: scrapeable + black-box-dumpable
        self.control = RolloutServer(self.status,
                                     self.verdict_state).start()
        self._control_lease = None
        if register:
            try:
                _, self._control_lease = \
                    _membership.register_endpoint(
                        self._kv, ROLLOUT_ROLE, int(control_slots),
                        self.control.endpoint, ttl=2.0, timeout=5.0)
            except Exception as e:
                import sys
                print("paddle_tpu.serving.rollout: control-lease "
                      "registration failed (%r); serving "
                      "unregistered on %s"
                      % (e, self.control.endpoint), file=sys.stderr)

    # -- introspection -----------------------------------------------------
    def status(self):
        """Controller state snapshot (also the DUMP verb's ``state``
        payload): phase, candidate cells, verdicts, respawn/kill
        ledger, terminal reason."""
        with self._lock:
            return {
                "phase": self.phase,
                "version": self.version,
                "candidates": [
                    {"slot": c.slot, "endpoint": c.endpoint,
                     "shadow": bool(getattr(c.engine, "shadow",
                                            False))}
                    for c in self._cands],
                "verdicts": {p: dict(v)
                             for p, v in self.verdicts.items()},
                "respawns": self.respawns,
                "kills": self.kills,
                "reason": self.reason,
                "convergence_s": self.convergence_s,
                "mirror": self.router.mirror_status()["mirror"],
            }

    def verdict_state(self):
        """The VERD verb's payload: terminal-or-live phase plus the
        per-phase verdict dicts decided so far."""
        with self._lock:
            return {"phase": self.phase, "version": self.version,
                    "verdicts": {p: dict(v)
                                 for p, v in self.verdicts.items()}}

    def force_fail(self, reason="forced"):
        """Force the NEXT pending verdict to FAIL (the operator's big
        red button, and the test hook proving a rollout aborted in
        shadow serves zero candidate-only tokens)."""
        with self._lock:
            self._forced = ("FAIL", str(reason))

    # -- the pipeline ------------------------------------------------------
    def run(self):
        """Drive the full rollout; returns the terminal ``status()``.
        Raises RuntimeError when no flight recorder is armed — the
        verdict's evidence chain is not optional."""
        rec = _monrt.recorder()
        if rec is None:
            raise RuntimeError(
                "rollout requires an armed flight recorder "
                "(monitor.session or --flag monitor_record): delta "
                "verdicts are decided from recorder rows")
        self._t0 = time.time()
        try:
            self._set_phase("boot")
            self._boot_candidates(self.candidates, shadow=True)

            verdict = self._phase_verdict("shadow", rec)
            if verdict != "PASS":
                return self._rollback("shadow verdict %s" % verdict)

            cdelta = self._canary_delta()
            if cdelta is not None:
                verdict = self._phase_verdict("canary", rec,
                                              delta=cdelta)
                if verdict != "PASS":
                    return self._rollback("canary verdict %s"
                                          % verdict)
            else:
                self.verdicts["canary"] = {
                    "verdict": "PASS", "skipped": True,
                    "reason": "no canary-evaluable objectives"}

            self._set_phase("rolling")
            # candidates were scoring cells, not fleet capacity: the
            # promotion path is the autoscaler's chaos-gated roll
            self.router.disarm_mirror()
            self._retire_candidates()
            self.autoscaler.roll(self.artifact, self.version)
            last = self.autoscaler.wait_roll(
                timeout=max(120.0, 4 * self.verdict_timeout))
            if last.get("aborted"):
                return self._finish(
                    "rolled-back",
                    "roll aborted: %s" % last.get("reason"))
            self.autoscaler.wait_steady(
                timeout=max(60.0, 2 * self.verdict_timeout))
            self.convergence_s = time.time() - self._t0
            return self._finish("promoted", "verdicts passed")
        except Exception as e:
            if self.phase not in ("promoted", "rolled-back"):
                self._rollback("controller error: %r" % e)
            raise

    # -- phases ------------------------------------------------------------
    def _set_phase(self, phase, detail=None):
        with self._lock:
            self.phase = phase
        try:
            mix = self.autoscaler.status()["version_mix"]
        except Exception:
            mix = None
        _monrt.on_rollout(phase, self.version, detail=detail,
                          version_mix=mix,
                          convergence_s=self.convergence_s)

    def _canary_delta(self):
        """The canary-phase delta block: token agreement dropped (no
        mirrored pairs join during a real-traffic split) and the pair
        gate zeroed. None when nothing evaluable remains."""
        objs = [dict(o) for o in self.delta["objectives"]
                if o["metric"] != "token_agreement"]
        if not objs:
            return None
        d = dict(self.delta)
        d["objectives"] = objs
        d["min_pairs"] = 0
        return d

    def _phase_verdict(self, phase, rec, delta=None):
        """Arm the mirror for ``phase``, feed the delta evaluator from
        the flight recorder until its exactly-once verdict lands (or
        the timeout forces FAIL), reconciling chaos-killed candidates
        along the way. Returns "PASS"/"FAIL"."""
        delta = delta if delta is not None else self.delta
        self._set_phase(phase)
        if phase == "shadow":
            self.router.arm_shadow(self.version,
                                   fraction=self.shadow_fraction)
        else:
            # order is the contract: the shadow mirror disarms FIRST —
            # dropping the queued copy backlog wholesale (best-effort
            # by contract; at high mirror fractions that backlog is
            # unbounded and can NEVER be drained in bounded time) —
            # then the copies already admitted at candidate engines
            # retire while those engines are still shadow-stamped, and
            # only THEN do the cells flip to real serving. Flipping
            # first would let the drained tail retire as shadow=False
            # rows stamped with the candidate version: counterfeit
            # "canary-served" evidence that can satisfy the verdict's
            # request gate before a single real canary request was
            # sampled.
            self.router.disarm_mirror()
            self._drain_candidate_inflight()
            for c in list(self._cands):
                self._mark_cell(c, shadow=False)
            self.router.arm_canary(self.version,
                                   weight=self.canary_weight)
        self.router.wait_for_candidates(1, timeout=30.0)

        rule = _signals.DeltaRule(delta, self.version, phase=phase)
        sig = _signals.Signals(rules=[rule])
        if self._capture:
            from ..monitor import forensics as _forensics
            _forensics.attach(sig, kv_endpoint=self._kv_endpoint,
                              out_dir=self._capture_dir)
        deadline = time.monotonic() + self.verdict_timeout
        while rule.verdict is None:
            with self._lock:
                forced = self._forced
            if forced is not None:
                rule.force(*forced)
            elif time.monotonic() > deadline:
                rule.force("FAIL", "verdict timeout (%gs)"
                           % self.verdict_timeout)
            self._feed(sig, rec)
            self._consult_chaos(phase)
            self._reconcile(shadow=(phase == "shadow"))
            sig.evaluate(now=time.time())
            if rule.verdict is None:
                time.sleep(0.02)
        report = dict(rule.report or {})
        report["verdict"] = rule.verdict
        with self._lock:
            self.verdicts[phase] = report
        return rule.verdict

    def _feed(self, sig, rec):
        self._cursor, rows, _lost = rec.events_since(self._cursor)
        if rows:
            sig.feed_events(rows)

    def _consult_chaos(self, phase):
        """Mid-phase kill gates: target ``shadow`` fires on joined
        mirror pairs, ``canary`` on canary-SAMPLED requests (the
        submit-time counter: the served counter trails the verdict's
        evidence rows, so a small ``after`` could lose the race
        against a fast verdict and never fire) — one live candidate
        cell hard-crashes (lease dies with it; the router's existing
        down/resubmission path takes over)."""
        plan = _faults._ACTIVE
        if plan is None or not self._cands:
            return
        value = self.router.stats["mirror_pairs"] \
            if phase == "shadow" \
            else self.router.stats["canary"]
        if plan.should_kill(phase, value):
            cell = self._cands[0]
            self.kills += 1
            cell.crash()

    def _reconcile(self, shadow):
        """Reap dead candidate cells; respawn (bounded) from the same
        artifact so the verdict's evidence keeps accumulating after a
        chaos kill."""
        for cell in list(self._cands):
            if cell.lease.lost or cell.lease._stop.is_set():
                with self._lock:
                    self._cands.remove(cell)
        while len(self._cands) < self.candidates \
                and self.respawns < self.max_respawns:
            self.respawns += 1
            try:
                self._spawn_candidate(shadow=shadow)
            except Exception:
                break              # no slot yet (tombstone TTL): retry
                                   # next loop round via the same gate

    def _boot_candidates(self, n, shadow):
        for _ in range(int(n)):
            self._spawn_candidate(shadow=shadow)

    def _spawn_candidate(self, shadow):
        cell = Replica(self._kv, self.artifact,
                       desired=self._cand_span, slots=self._slots,
                       ttl=self._ttl, role=CANDIDATE_ROLE,
                       version=self.version, shadow=shadow,
                       **self._engine_kwargs)
        try:
            # pre-pay the XLA compiles NOW, before the mirror feeds
            # the cell: a cold candidate stalls its first admissions
            # by full compiles, and those stalls would land in the
            # candidate's TTFT samples — the delta verdict would then
            # judge the compiler, not the artifact
            cell.engine.warmup()
        except (AttributeError, RuntimeError):
            # factory engine without warmup, or a mirror copy raced
            # in through the already-registered lease: compile lazily
            pass
        self._prime(cell)
        with self._lock:
            self.cells.append(cell)
            self._cands.append(cell)
        return cell

    @staticmethod
    def _prime(cell, timeout=30.0):
        """Run ONE real request end-to-end through the fresh cell
        before it joins the mirror: warmup() covers the decode
        dispatch paths, but the first admission still pays the lazy
        prefill compile — seconds of TTFT that would otherwise land
        in the candidate's first (and, with a small ``min_pairs``
        gate, verdict-deciding) delta samples. The priming row is
        stamped version "__prime__" so delta_samples_from_events
        counts it on NEITHER side."""
        eng = cell.engine
        try:
            ver = eng.version
            eng.version = "__prime__"
        except AttributeError:
            return                 # factory engine: nothing to prime
        try:
            req = eng.submit([1], 2)
            deadline = time.monotonic() + timeout
            while not req.done() and time.monotonic() < deadline:
                time.sleep(0.01)
        except Exception:
            pass                   # priming is best-effort
        finally:
            eng.version = ver

    @staticmethod
    def _mark_cell(cell, shadow):
        cell.shadow = shadow
        try:
            cell.engine.shadow = shadow
        except AttributeError:
            pass

    def _drain_candidate_inflight(self, timeout=10.0):
        """Bounded wait for copies already ADMITTED at the candidate
        engines to retire before the cells flip to real serving —
        their rows must land while the engines are still
        shadow-stamped. Unlike the router's queued backlog (which
        disarm_mirror has already dropped), this set is bounded by
        the per-candidate mirror window, so the wait converges."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            busy = False
            for cell in list(self._cands):
                try:
                    with cell.server._lock:
                        busy = any(not j["req"].done()
                                   for j in cell.server._jobs.values())
                except Exception:
                    continue
                if busy:
                    break
            if not busy:
                return
            time.sleep(0.02)

    # -- terminal ----------------------------------------------------------
    def _rollback(self, reason):
        # order is the contract: the mirror disarms FIRST — sampling
        # stops and candidate slots leave dispatch — so a rollout
        # aborted in shadow has served ZERO candidate-only tokens, and
        # unfinished canary requests resubmit to incumbents via the
        # journal (exactly-once through the rollback)
        self.router.disarm_mirror()
        self._retire_candidates()
        return self._finish("rolled-back", reason)

    def _retire_candidates(self):
        with self._lock:
            cells, self._cands = self._cands, []
        for cell in cells:
            try:
                cell.shutdown()
            except Exception:
                pass

    def _finish(self, phase, reason):
        with self._lock:
            self.reason = reason
        self._set_phase(phase, detail=reason)
        return self.status()

    # -- lifecycle ---------------------------------------------------------
    def close(self):
        if self.phase not in ("promoted", "rolled-back", "idle"):
            try:
                self._rollback("controller closed")
            except Exception:
                pass
        else:
            self._retire_candidates()
        if self._control_lease is not None:
            try:
                self._control_lease.revoke()
            except (ConnectionError, OSError):
                pass
        try:
            self.control.stop()
        except OSError:
            pass
        for c in list(self.cells):
            try:
                c.shutdown()
            except Exception:
                pass
        self._kv.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
