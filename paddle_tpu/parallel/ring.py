"""Ring attention: sequence/context parallelism over the ``sp`` mesh axis.

Long-context design (task requirement; beyond the 2018 reference, which
handled long sequences only by LoD batching — SURVEY.md §5.7): the sequence
axis is sharded across devices; each device holds a Q shard and passes its
K/V shard around the ring with ``ppermute`` while accumulating
flash-attention-style streaming softmax statistics (running max + running
denominator), so the full [T, T] score matrix never materializes and K/V
transfers overlap with the blockwise matmuls (Liu et al., Ring Attention
with Blockwise Transformers).
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax import shard_map
from jax.sharding import PartitionSpec as P


def _blockwise_attn_update(q, k, v, m_prev, l_prev, o_prev, scale,
                           mask_value=-1e30, block_mask=None):
    """One streaming-softmax accumulation step.
    q [B,H,Tq,D], k/v [B,H,Tk,D]; m,l running max/denominator [B,H,Tq]."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if block_mask is not None:
        s = jnp.where(block_mask, s, mask_value)
    m_cur = jnp.max(s, axis=-1)
    m_new = jnp.maximum(m_prev, m_cur)
    p = jnp.exp(s - m_new[..., None])
    correction = jnp.exp(m_prev - m_new)
    l_new = l_prev * correction + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    o_new = o_prev * correction[..., None] + pv
    return m_new, l_new, o_new


def _ring_attention_sharded(q, k, v, axis_name, causal, scale):
    """Per-shard body (inside shard_map). q/k/v: [B, H, T_local, D]."""
    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    b, h, t_local, d = q.shape

    m = jnp.full((b, h, t_local), -jnp.inf, jnp.float32)
    l = jnp.zeros((b, h, t_local), jnp.float32)
    o = jnp.zeros((b, h, t_local, d), jnp.float32)

    def ring_step(i, carry):
        m, l, o, k_cur, v_cur = carry
        src_idx = (my_idx - i) % axis_size   # whose K/V block we hold now
        if causal:
            # global positions: q_pos = my_idx*T + tq, k_pos = src*T + tk
            q_pos = my_idx * t_local + jnp.arange(t_local)
            k_pos = src_idx * t_local + jnp.arange(t_local)
            block_mask = q_pos[:, None] >= k_pos[None, :]
            block_mask = jnp.broadcast_to(block_mask,
                                          (b, h, t_local, t_local))
        else:
            block_mask = None
        m, l, o = _blockwise_attn_update(q, k_cur, v_cur, m, l, o, scale,
                                         block_mask=block_mask)
        # rotate K/V shards around the ring (overlaps with next matmul
        # after XLA latency-hiding scheduling)
        perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return m, l, o, k_nxt, v_nxt

    m, l, o, _, _ = lax.fori_loop(0, axis_size, ring_step, (m, l, o, k, v))
    out = o / jnp.maximum(l[..., None], 1e-30)
    return out.astype(q.dtype)


def ring_attention(q, k, v, mesh, axis_name="sp", causal=False, scale=None,
                   batch_axis=None):
    """q,k,v: [B, H, T, D] with T sharded on `axis_name`. Returns [B,H,T,D]
    with the same sharding. Pass batch_axis="dp" when the mesh also data-
    parallelizes the batch dim, so shard_map doesn't gather it."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    spec = P(batch_axis, None, axis_name, None)
    fn = shard_map(
        functools.partial(_ring_attention_sharded, axis_name=axis_name,
                          causal=causal, scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return fn(q, k, v)


def ulysses_attention(q, k, v, mesh, axis_name="sp", causal=False,
                      scale=None, batch_axis=None):
    """DeepSpeed-Ulysses style sequence parallelism: all-to-all swaps the
    sharded axis from sequence to heads, runs full local attention, then
    swaps back. Better when H >= axis_size and T is moderate."""
    if scale is None:
        scale = q.shape[-1] ** -0.5

    def body(q, k, v):
        # local shards [B, H, T/s, D] → a2a → [B, H/s, T, D]
        def a2a(x, split, concat):
            return lax.all_to_all(x, axis_name, split_axis=split,
                                  concat_axis=concat, tiled=True)
        q2, k2, v2 = (a2a(t, 1, 2) for t in (q, k, v))
        s = jnp.einsum("bhqd,bhkd->bhqk", q2, k2,
                       preferred_element_type=jnp.float32) * scale
        if causal:
            tq = s.shape[-2]
            mask = jnp.tril(jnp.ones((tq, tq), bool))
            s = jnp.where(mask, s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bhqk,bhkd->bhqd", p, v2.astype(jnp.float32))
        return a2a(o.astype(q.dtype), 2, 1)

    spec = P(batch_axis, None, axis_name, None)
    fn = shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                   out_specs=spec, check_vma=False)
    return fn(q, k, v)
