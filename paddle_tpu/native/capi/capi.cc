// C inference API — the non-Python deployment entry point.
//
// Reference capability: paddle/capi/gradient_machine.h:36-62
// (paddle_gradient_machine_create_for_inference / _forward): a C program
// loads a trained model and runs forward passes. Here the engine is
// XLA-through-JAX, so the C ABI embeds a CPython interpreter and drives
// the same `fluid.io.load_inference_model` + Executor path a Python
// deployment would use — one process, one interpreter, no IPC. The C
// surface stays engine-agnostic: floats in, floats out.
//
//   void* pt_predictor_create(const char* model_dir);
//   int   pt_predictor_run(void* p,
//                          const float* in, const int64_t* shape, int nd,
//                          float* out, int64_t out_cap,
//                          int64_t* out_shape, int* out_nd);
//       out_shape must have at least 8 slots (max supported rank);
//       higher-rank fetches fail with an error instead of truncating.
//   void  pt_predictor_destroy(void* p);
//   const char* pt_last_error();
//
// Single-feed single-fetch (the common serving shape); multi-io can layer
// on the same mechanism. Thread-safety: calls serialize on the GIL.

#include <Python.h>

#include <cstdint>
#include <cstring>
#include <string>

namespace {

std::string g_error;

void set_error_from_python() {
  PyObject *type, *value, *tb;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  PyObject* s = value ? PyObject_Str(value) : nullptr;
  const char* msg = s ? PyUnicode_AsUTF8(s) : nullptr;
  g_error = msg ? msg : "unknown python error";
  PyErr_Clear();  // AsUTF8 may raise; never leave an exception pending
  Py_XDECREF(s);
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
}

const char kHelper[] = R"PY(
import os
import numpy as np
import paddle_tpu as fluid

class _CPredictor:
    """Holds a loaded inference program + scope; run() takes/returns
    float32 numpy arrays (fluid.io.load_inference_model serving path)."""

    def __init__(self, model_dir):
        self.scope = fluid.Scope()
        self.exe = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(self.scope):
            prog, feeds, fetches = fluid.io.load_inference_model(
                model_dir, self.exe)
        self.prog, self.feeds, self.fetches = prog, feeds, fetches

    def run(self, buf, shape):
        # zero-copy in: `buf` is a C memoryview over the caller's floats
        x = np.frombuffer(buf, np.float32).reshape(shape).copy()
        with fluid.scope_guard(self.scope):
            out, = self.exe.run(self.prog, feed={self.feeds[0]: x},
                                fetch_list=self.fetches)
        out = np.ascontiguousarray(np.asarray(out), np.float32)
        return out.tobytes(), list(out.shape)
)PY";

struct Predictor {
  PyObject* obj;  // _CPredictor instance
};

PyObject* g_namespace = nullptr;

bool g_we_initialized = false;

bool ensure_python() {
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
    g_we_initialized = true;
  }
  if (g_namespace == nullptr) {
    PyObject* main_mod = PyImport_AddModule("__main__");
    g_namespace = PyModule_GetDict(main_mod);
    Py_INCREF(g_namespace);
    PyObject* r = PyRun_String(kHelper, Py_file_input, g_namespace,
                               g_namespace);
    if (r == nullptr) {
      set_error_from_python();
      Py_CLEAR(g_namespace);
      return false;
    }
    Py_DECREF(r);
  }
  return true;
}

}  // namespace

extern "C" {

const char* pt_last_error() { return g_error.c_str(); }

void* pt_predictor_create(const char* model_dir) {
  bool had_python = Py_IsInitialized();
  PyGILState_STATE gil = PyGILState_LOCKED;
  if (had_python) gil = PyGILState_Ensure();
  void* result = nullptr;
  bool ok = ensure_python();
  if (ok) {
    PyObject* cls = PyDict_GetItemString(g_namespace, "_CPredictor");
    PyObject* obj =
        cls ? PyObject_CallFunction(cls, "s", model_dir) : nullptr;
    if (obj == nullptr) {
      set_error_from_python();
    } else {
      Predictor* p = new Predictor{obj};
      result = p;
    }
  }
  if (had_python) {
    PyGILState_Release(gil);
  } else if (ok || g_we_initialized) {
    // we created the interpreter on this thread: release the GIL so other
    // threads' PyGILState_Ensure can proceed (serving pattern: create on
    // main, run on workers)
    PyEval_SaveThread();
  }
  return result;
}

int pt_predictor_run(void* handle, const float* in, const int64_t* shape,
                     int nd, float* out, int64_t out_cap,
                     int64_t* out_shape, int* out_nd) {
  Predictor* p = static_cast<Predictor*>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  int64_t n = 1;
  for (int i = 0; i < nd; ++i) n *= shape[i];

  // buffer-protocol marshalling: no per-element boxing on the hot path
  PyObject* mv = PyMemoryView_FromMemory(
      const_cast<char*>(reinterpret_cast<const char*>(in)),
      n * int64_t(sizeof(float)), PyBUF_READ);
  PyObject* shp = PyList_New(nd);
  for (int i = 0; i < nd; ++i) {
    PyList_SET_ITEM(shp, i, PyLong_FromLongLong(shape[i]));
  }
  PyObject* res = PyObject_CallMethod(p->obj, "run", "OO", mv, shp);
  Py_DECREF(mv);
  Py_DECREF(shp);
  if (res == nullptr) {
    set_error_from_python();
  } else {
    PyObject* vals = PyTuple_GetItem(res, 0);  // bytes
    PyObject* oshp = PyTuple_GetItem(res, 1);
    char* data = nullptr;
    Py_ssize_t nbytes = 0;
    PyBytes_AsStringAndSize(vals, &data, &nbytes);
    int64_t out_n = nbytes / int64_t(sizeof(float));
    int ond = int(PyList_Size(oshp));
    if (out_n > out_cap) {
      g_error = "output buffer too small";
    } else if (ond > 8) {
      g_error = "output rank exceeds the 8-slot out_shape contract";
    } else {
      memcpy(out, data, size_t(nbytes));
      for (int i = 0; i < ond; ++i) {
        out_shape[i] = PyLong_AsLongLong(PyList_GetItem(oshp, i));
      }
      *out_nd = ond;
      rc = 0;
    }
    Py_DECREF(res);
  }
  PyGILState_Release(gil);
  return rc;
}

void pt_predictor_destroy(void* handle) {
  Predictor* p = static_cast<Predictor*>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  Py_XDECREF(p->obj);
  PyGILState_Release(gil);
  delete p;
}

}  // extern "C"
