"""Flight recorder: bounded, structured JSONL log of run events.

The black-box half of paddle_tpu.monitor: every structured event (run
metadata, per-step timing, compile/recompile, NaN-guard trips, stalls)
is one JSON object per line, written synchronously under a lock (events
are rare relative to their cost budgets: a step event per training step,
a compile event per recompilation). Bounded: past ``max_bytes`` the
recorder stops writing payload events and appends a single final
``truncated`` line carrying the dropped-event count, so a runaway run
cannot fill a disk while the log stays machine-parseable end to end.

Schema (every line):
  {"ts": <epoch seconds float>, "ev": "<type>", ...fields}
Event types written by the runtime:
  run_meta | devices | step | compile | xla_compile | nan_guard |
  stall | note | truncated
Event types written by the resilience tier (paddle_tpu.resilience):
  fault | retry | reconnect | rollback | resume | checkpoint
Event types written by the serving tier (paddle_tpu.serving):
  serving_step     one engine iteration (active/slots/queue_depth/
                   emitted/admitted/retired/dt, ambient trace id)
  serving_request  one request retired or failed (queue_wait/ttft/
                   tpot/tokens/prefill_chunks/prompt_len, the
                   REQUEST's trace id, error when failed)
"""

import collections
import json
import os
import threading
import time
import uuid

__all__ = ["FlightRecorder"]


def percentile_sorted(sorted_vals, q):
    """Nearest-rank percentile over an ASCENDING list; None when empty.
    Shared by the monitor and trace CLIs summarizing these logs."""
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]

_DEFAULT_MAX_BYTES = 64 << 20


_DEFAULT_RING = 2048


class FlightRecorder:
    def __init__(self, path, max_bytes=_DEFAULT_MAX_BYTES,
                 ring=_DEFAULT_RING):
        self.path = path
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        self._bytes = 0
        self._dropped = 0
        self._truncated_written = False
        # bounded in-memory tail of recent events, each stamped with a
        # monotonically increasing sequence number: the live scrape
        # surface (rpc METR serves "rows since cursor" from here, so a
        # fleet collector streams events without tailing N files). It
        # keeps filling past the on-disk byte cap — the cap bounds the
        # DISK, the ring is bounded by construction. ring_id names THIS
        # recorder's sequence space: monitor.enable() replaces the
        # recorder (sequence restarts) without the process restarting,
        # and a scraper whose cursor came from the OLD ring must learn
        # its cursor is meaningless rather than silently filter every
        # new row against it.
        self.ring_id = uuid.uuid4().hex[:12]
        self._ring = collections.deque(maxlen=int(ring))
        self._seq = 0
        d = os.path.dirname(os.path.abspath(path))
        if d:
            os.makedirs(d, exist_ok=True)
        # append mode: the byte budget must count what is ALREADY in the
        # file, or every re-enable()/restart hands the same log a fresh
        # max_bytes and the disk-bound guarantee is gone
        try:
            self._bytes = os.path.getsize(path)
        except OSError:
            pass
        self._f = open(path, "a", buffering=1)

    def record(self, ev, **fields):
        """Append one event. Non-JSON-able field values degrade to their
        repr — a telemetry write must never throw into the hot path."""
        rec = {"ts": time.time(), "ev": str(ev)}
        rec.update(fields)
        try:
            line = json.dumps(rec)
        except (TypeError, ValueError):
            rec = {k: (v if isinstance(
                v, (str, int, float, bool, type(None))) else repr(v))
                for k, v in rec.items()}
            line = json.dumps(rec)
        # budget in ENCODED bytes (json.dumps default-escapes to ASCII,
        # but field values may carry multibyte text; getsize() at open
        # is bytes too, so the units must match)
        nb = len(line.encode("utf-8", "surrogatepass")) + 1
        with self._lock:
            if self._f is None:
                return False
            # the ring sees every event the recorder accepted, byte
            # cap or not. It stores the ENCODED line (the same bytes
            # the file gets, degraded reprs included): parsing happens
            # at scrape time in events_since — bounded by the ring and
            # rare — not once per hot-path record
            self._seq += 1
            self._ring.append((self._seq, line))
            if self._truncated_written:
                # the truncated marker is FINAL: smaller events after a
                # large overflowing one must not slip in past it, or the
                # marker lies about where recording stopped
                self._dropped += 1
                return False
            if self._bytes + nb > self.max_bytes:
                self._dropped += 1
                # in-band cap marker (profiler TRACE TRUNCATED parity)
                self._truncated_written = True
                tr = json.dumps({"ts": time.time(), "ev": "truncated",
                                 "max_bytes": self.max_bytes})
                try:
                    self._f.write(tr + "\n")
                except OSError:
                    pass                 # see below: never throw
                self._bytes += len(tr) + 1
                return False
            try:
                self._f.write(line + "\n")
            except OSError:
                # the never-throw contract covers the WRITE too: a full
                # disk must degrade to a counted drop, not propagate
                # into an engine loop / executor step and strand its
                # callers (the serving scheduler pops finished requests
                # before recording them)
                self._dropped += 1
                return False
            self._bytes += nb
            return True

    @property
    def dropped(self):
        with self._lock:
            return self._dropped

    def events_since(self, cursor=None):
        """Ring rows newer than ``cursor`` (a sequence number from a
        previous call; None = everything still in the ring). Returns
        ``(new_cursor, rows, lost)`` where ``lost`` counts rows that
        aged out of the bounded ring between scrapes — a slow scraper
        learns it missed events instead of silently under-counting."""
        with self._lock:
            if cursor is None:
                rows = list(self._ring)
                lost = 0
            else:
                cursor = int(cursor)
                rows = [(s, r) for s, r in self._ring if s > cursor]
                oldest = self._ring[0][0] if self._ring else \
                    self._seq + 1
                lost = max(0, oldest - cursor - 1)
            new_cursor = rows[-1][0] if rows else \
                (self._seq if cursor is None else max(cursor,
                                                      self._seq))
        # parse OUTSIDE the lock: a full-ring scrape decodes up to
        # `ring` lines, and record() on the hot path must not wait
        # behind it
        return new_cursor, [json.loads(r) for _, r in rows], lost

    def flush(self):
        with self._lock:
            if self._f is not None:
                self._f.flush()

    def close(self):
        # no trailing note: the truncated marker (written at the first
        # drop) is the documented FINAL line of a capped log; the
        # in-process drop count stays readable via .dropped
        with self._lock:
            if self._f is None:
                return
            try:
                self._f.close()
            finally:
                self._f = None


def read_jsonl_tolerant(path):
    """Parse a flight-recorder / span log that may still be LIVE: a
    writer killed mid-record leaves a truncated trailing line (and a
    crash mid-flush can tear an interior one). Malformed lines are
    skipped and counted, not fatal. Returns (events, skipped)."""
    events, skipped = [], 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                skipped += 1
                continue
            if not isinstance(rec, dict) or "ts" not in rec \
                    or "ev" not in rec:
                skipped += 1
                continue
            events.append(rec)
    return events, skipped


def read_jsonl(path):
    """Parse a flight-recorder log → list of event dicts. Raises
    ValueError naming the first malformed line (schema guarantee the
    tests pin)."""
    events = []
    with open(path) as f:
        for i, line in enumerate(f):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                raise ValueError(
                    "%s line %d is not valid JSON: %s" % (path, i + 1, e))
            if not isinstance(rec, dict) or "ts" not in rec \
                    or "ev" not in rec:
                raise ValueError(
                    "%s line %d missing ts/ev fields" % (path, i + 1))
            events.append(rec)
    return events
