"""Program visualization/debug dumps.

Reference parity: python/paddle/fluid/debuger.py (pprint_program_codes,
draw_block_graphviz) + graphviz.py. Emits Graphviz .dot text directly (no
graphviz binding needed to write the file; render with `dot -Tpng` if
installed) and a readable pseudo-code dump of a Program.
"""

import html


def _esc(s):
    return html.escape(str(s), quote=True)


def draw_block_graphviz(block, highlights=None, path=None):
    """Block -> Graphviz dot source. Ops are boxes, vars are ellipses
    (parameters shaded), edges follow def-use. Returns the dot text;
    writes it to `path` when given (reference debuger.py:draw_block_graphviz
    contract)."""
    highlights = set(highlights or [])
    lines = ["digraph G {", "  rankdir=TB;",
             '  node [fontsize=10, fontname="helvetica"];']
    var_ids = {}
    for i, (name, var) in enumerate(sorted(block.vars.items())):
        var_ids[name] = "var_%d" % i
        shape_txt = "?" if var.shape is None else list(var.shape)
        style = "filled"
        fill = "#eeeeee"
        from .core.program import Parameter
        if isinstance(var, Parameter):
            fill = "#b3d9ff"
        if name in highlights:
            fill = "#ffcccc"
        lines.append(
            '  %s [label="%s\\n%s %s", shape=ellipse, style=%s, '
            'fillcolor="%s"];'
            % (var_ids[name], _esc(name), _esc(var.dtype), _esc(shape_txt),
               style, fill))
    for j, op in enumerate(block.ops):
        op_id = "op_%d" % j
        lines.append(
            '  %s [label="%d: %s", shape=box, style=filled, '
            'fillcolor="#ccffcc"];' % (op_id, j, _esc(op.type)))
        for names in op.inputs.values():
            for n in names:
                if n in var_ids:
                    lines.append("  %s -> %s;" % (var_ids[n], op_id))
        for names in op.outputs.values():
            for n in names:
                if n in var_ids:
                    lines.append("  %s -> %s;" % (op_id, var_ids[n]))
    lines.append("}")
    dot = "\n".join(lines)
    if path:
        with open(path, "w") as f:
            f.write(dot)
    return dot


def pprint_program_codes(program):
    """Readable pseudo-code for every block (debuger.py:pprint_program_codes
    parity): one `outs = op_type(ins) {attrs}` line per op."""
    out = []
    for bi in range(program.num_blocks):
        block = program.block(bi)
        out.append("// block %d (parent %d)" % (block.idx, block.parent_idx))
        for op in block.ops:
            ins = ", ".join(
                "%s=%s" % (slot, names)
                for slot, names in sorted(op.inputs.items()) if names)
            outs = ", ".join(
                "%s=%s" % (slot, names)
                for slot, names in sorted(op.outputs.items()) if names)
            attrs = {k: v for k, v in sorted(op.attrs.items())
                     if not k.startswith("_") and k != "sub_block"}
            out.append("%s = %s(%s) %s" % (outs or "()", op.type,
                                           ins, attrs or ""))
        out.append("")
    return "\n".join(out)


def draw_program(program, path=None):
    """Whole-program convenience: dot for the global block."""
    return draw_block_graphviz(program.global_block(), path=path)
