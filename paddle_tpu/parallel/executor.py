"""ParallelExecutor: SPMD data(+tensor)-parallel program execution.

Reference parity: python/paddle/fluid/parallel_executor.py:25-130 +
framework/parallel_executor.cc:54-203. The reference replicates the graph
per GPU, broadcasts params, splits the feed batch (SplitLoDTensor) and
inserts NCCL all-reduce per gradient. Here: ONE jitted step function with
input shardings — batch feeds sharded on the mesh's ``dp`` axis, state
replicated (or sharded by `parallel.shard` hints for TP) — and XLA GSPMD
derives every collective, overlapped with compute.
"""

import numpy as np
import jax
from jax.sharding import NamedSharding, PartitionSpec

from .mesh import (make_mesh, default_mesh, set_default_mesh,
                   spec_to_named_sharding)
from ..core.program import default_main_program, Variable
from ..core.scope import global_scope
from ..core.executor import Executor, as_numpy, _feed_signature
from ..core.lod import LoDTensor


class ParallelExecutor:
    def __init__(self, use_cuda=False, loss_name=None, main_program=None,
                 share_vars_from=None, num_trainers=1, trainer_id=0,
                 mesh=None, scope=None, use_tpu=True, strategy=None,
                 **kwargs):
        # `use_cuda` is accepted as the reference's legacy "use accelerator"
        # flag; device choice here is the mesh's. Anything we can't honor is
        # rejected loudly instead of silently dropped.
        if kwargs:
            raise TypeError(
                "unsupported ParallelExecutor arguments: %r"
                % sorted(kwargs))
        if num_trainers != 1 and jax.process_count() != num_trainers:
            raise ValueError(
                "num_trainers=%d but this process group has %d processes; "
                "multi-trainer mode requires jax.distributed.initialize() "
                "across exactly num_trainers hosts"
                % (num_trainers, jax.process_count()))
        self.num_trainers = num_trainers
        self.trainer_id = trainer_id if num_trainers > 1 \
            else jax.process_index()
        self.mesh = mesh or default_mesh() or make_mesh()
        if default_mesh() is None:
            set_default_mesh(self.mesh)
        self._program = main_program or default_main_program()
        if share_vars_from is not None:
            # reference semantics (parallel_executor.py share_vars_from):
            # reuse the parameter scope of an existing executor (e.g. share
            # train params with a test ParallelExecutor).
            scope = share_vars_from._scope
        self._scope = scope or global_scope()
        self._exe = Executor.__new__(Executor)
        from ..core.places import TPUPlace, CPUPlace
        dev = np.ravel(self.mesh.devices)[0]
        self._exe.place = (TPUPlace(0) if dev.platform == "tpu"
                           else CPUPlace())
        self._exe._cache = {}
        self._exe._rng_counter = 0
        self._exe._mesh = self.mesh   # lowerings (sp/pp/ep ops) read this
        self._cache = {}
        # feed-plan cache (plans only, no device commit: pexe feeds get
        # mesh shardings downstream) — repeated-shape batches skip the
        # per-call normalization derivation
        from ..core.executor import FeedPlanCache
        self._feed_plans = FeedPlanCache(device_fn=None)
        self._loss_name = loss_name
        # DistributedStrategy execution knobs (mesh axes are consumed by
        # the model builders; these two belong to the executor)
        self._accum_steps = max(
            1, int(getattr(strategy, "gradient_accumulation_steps", 1)))
        # How the loss is normalized, for ragged-LoD accumulation
        # weighting: None (reject ragged-unequal splits), "sequence",
        # "token", or "token:<feed_name>" — see
        # Executor._lower_with_grad_accum.
        self._accum_loss_norm = getattr(
            strategy, "gradient_accumulation_loss_norm", None)
        if self._accum_loss_norm is not None and not (
                self._accum_loss_norm == "sequence"
                or self._accum_loss_norm == "token"
                or self._accum_loss_norm.startswith("token:")):
            raise ValueError(
                "gradient_accumulation_loss_norm must be 'sequence', "
                "'token', or 'token:<feed_name>'; got %r"
                % (self._accum_loss_norm,))
        # use_bf16_compute=True pins AMP on for THIS executor's traces
        # (restored after each build — the global flag is not leaked);
        # False (the default) leaves the ambient AMP setting alone
        self._force_bf16 = bool(getattr(strategy, "use_bf16_compute",
                                        False)) or None

    @property
    def device_count(self):
        return int(np.prod(self.mesh.devices.shape))

    def _data_sharding(self):
        axes = [a for a in ("dp",) if a in self.mesh.axis_names]
        return NamedSharding(self.mesh,
                             PartitionSpec(axes[0] if axes else None))

    def _state_sharding(self, name):
        spec = self._program._sharding_hints.get(name)
        return spec_to_named_sharding(self.mesh, spec)

    def _check_accum_weights(self, feed_arrays):
        """Host-side guard for ragged gradient accumulation (concrete
        per-microbatch token totals from _normalize_feeds).

        Equal-weight averaging of microbatch losses is only exact when
        every microbatch carries equal weight in the full-batch loss;
        with unequal token totals that holds for per-sequence-mean
        losses but silently mis-scales token-normalized ones. So:
        unequal totals require an explicit loss_norm, and 'token' with
        several disagreeing LoD feeds requires naming the one that
        normalizes the loss."""
        _TOK = "@ACCUM_TOKENS"
        toks = {n[:-len(_TOK)]: np.asarray(v)
                for n, v in feed_arrays.items() if n.endswith(_TOK)}
        norm = self._accum_loss_norm
        if norm is None:
            ragged = sorted(n for n, t in toks.items()
                            if not np.all(t == t[0]))
            if ragged:
                raise ValueError(
                    "gradient accumulation with ragged LoD feeds: "
                    "microbatch token totals are unequal for %s. Equal "
                    "microbatch weighting is only exact for per-"
                    "sequence-mean losses. Set DistributedStrategy."
                    "gradient_accumulation_loss_norm='sequence' (loss "
                    "is a mean over sequences) or 'token' (loss is a "
                    "mean over tokens; microbatches are weighted by "
                    "their true token counts)." % ragged)
        elif norm == "token" and len(toks) > 1:
            rep = {tuple(t.tolist()) for t in toks.values()}
            if len(rep) > 1:
                raise ValueError(
                    "gradient_accumulation_loss_norm='token' is "
                    "ambiguous: LoD feeds %s have different microbatch "
                    "token totals. Name the feed the loss normalizes "
                    "over: 'token:<feed_name>'." % sorted(toks))

    def run(self, fetch_list, feed=None, feed_dict=None, return_numpy=True):
        from ..trace import runtime as _trc
        trc = _trc._TRACER
        if trc is None:
            return self._run_impl(fetch_list, feed, feed_dict,
                                  return_numpy)
        # distributed-trace root span per step (see core Executor.run)
        with trc.span("pexe.step"):
            return self._run_impl(fetch_list, feed, feed_dict,
                                  return_numpy)

    @staticmethod
    def _local_value(v):
        """Host view of one fetched value. A replicated output's
        sharding spans remote devices; its local shard IS the value. A
        dp-SHARDED fetch has no local full value: with FLAGS
        gather_sharded_fetches on, all-gather it so every process
        fetches the merged global array (the reference merged fetched
        tensors across devices, parallel_executor.cc:190-197); default
        stays the loud refusal rather than handing back 1/N of the
        batch."""
        from ..flags import get_flag
        if jax.process_count() > 1 and isinstance(v, jax.Array) \
                and not v.is_fully_addressable:
            if not v.sharding.is_fully_replicated:
                if get_flag("gather_sharded_fetches"):
                    from jax.experimental import multihost_utils
                    return np.asarray(
                        multihost_utils.process_allgather(
                            v, tiled=True))
                raise NotImplementedError(
                    "fetching a cross-process SHARDED value (spec %s) "
                    "is not supported — fetch replicated values "
                    "(losses/metrics), gather in-graph first, or set "
                    "PADDLE_TPU_GATHER_SHARDED_FETCHES=1 to all-"
                    "gather at fetch time" % (v.sharding.spec,))
            return np.asarray(list(v.addressable_shards)[0].data)
        return v

    @staticmethod
    def _to_global(v, sh):
        """Place one host/device value per its target sharding.
        Steady-state device outputs pass through (committed GSPMD
        layouts stay; a multi-process array cannot be resharded
        host-side anyway); an addressable but mis-placed array (e.g. a
        single-device startup output vs a tp sharding hint) is laid
        out per the hint. On a multi-process (multi-host) mesh, host
        values become GLOBAL arrays via make_array_from_callback —
        every process passes the same full array (the reference's
        same-data-every-trainer contract, BCastParamsToGPUs parity)
        and keeps only its addressable shards."""
        multiproc = jax.process_count() > 1
        if isinstance(v, jax.Array):
            if not v.is_fully_addressable or v.sharding == sh:
                return v
            if multiproc:
                v = np.asarray(v)
            else:
                return jax.device_put(v, sh)
        if multiproc:
            arr = np.asarray(v)
            return jax.make_array_from_callback(
                arr.shape, sh, lambda idx, _a=arr: _a[idx])
        return jax.device_put(v, sh)

    # -- megastep execution (ISSUE 7) ----------------------------------
    def run_steps(self, fetch_list, feeds=None, return_numpy=True,
                  k=None):
        """K logical steps in ONE sharded device dispatch — the
        ParallelExecutor twin of ``Executor.run_steps`` (same feeds
        contract: a list of K per-step feed dicts, or one pre-stacked
        ``[k, ...]`` dict plus ``k``). The scanned step body is the
        same GSPMD-sharded program ``run()`` compiles; batch feeds
        shard on the mesh's ``dp`` axis along dim 1 (dim 0 is the scan
        dim). Returns K per-step fetch lists. Async double buffering
        rides the same ``megastep_inflight`` window as the core
        executor when ``return_numpy=False``."""
        from ..core.executor import Executor as _Exe
        feeds, k = _Exe._check_run_steps_args(feeds, k)
        from ..trace import runtime as _trc
        trc = _trc._TRACER
        if trc is None:
            return self._run_steps_impl(fetch_list, feeds, k,
                                        return_numpy)
        with trc.span("pexe.step", k=k):
            return self._run_steps_impl(fetch_list, feeds, k,
                                        return_numpy)

    def _run_steps_impl(self, fetch_list, feeds, k, return_numpy):
        import time as _time
        from ..core.executor import (Executor as _Exe, _flag_on,
                                     _stack_step_feeds,
                                     _stage_prestacked_feeds,
                                     _step_costs_safe)
        if self._accum_steps > 1:
            raise ValueError(
                "run_steps does not compose with gradient_"
                "accumulation_steps=%d: the megastep scan would nest "
                "the accumulation scan and change the optimizer "
                "cadence. Megastep K already amortizes dispatch; use "
                "one or the other." % self._accum_steps)
        program = self._program
        scope = self._scope
        fetch_names = tuple(
            f.name if isinstance(f, Variable) else str(f)
            for f in (fetch_list or []))
        if isinstance(feeds, dict):
            feeds_k, static_info, sig = _stage_prestacked_feeds(feeds, k)
        else:
            feeds_k, static_info, sig = _stack_step_feeds(
                feeds, plan_cache=self._feed_plans)

        dp = 1
        if "dp" in self.mesh.axis_names:
            dp = self.mesh.shape["dp"]
        # ragged LoD buffers stay replicated (SplitLoDTensor parity,
        # same classification as _run_impl): the derived @LOD/@ACCUM
        # vectors by suffix AND the flat token buffer itself, found by
        # its original per-step feed value being a LoDTensor — its dim
        # 1 is a data-dependent token total, not a batch dim
        lod_keys = {n for n in feeds_k
                    if n.endswith("@LOD") or n.endswith("@ACCUM_TOKENS")}
        if not isinstance(feeds, dict):
            lod_keys |= {n for f in feeds for n, v in (f or {}).items()
                         if isinstance(v, LoDTensor)}
        for n, v in feeds_k.items():
            if n not in lod_keys and getattr(v, "ndim", 0) >= 2 \
                    and v.shape[1] % dp != 0:
                raise ValueError(
                    "megastep feed %r per-step batch dim %d not "
                    "divisible by dp=%d" % (n, v.shape[1], dp))

        persistable = [v.name
                       for v in program.global_block().vars.values()
                       if v.persistable]
        state = {n: scope.find_var(n) for n in persistable
                 if scope.find_var(n) is not None}
        state_keys = tuple(sorted(state))
        hints = tuple(sorted(
            (n, tuple(v)) for n, v in program._sharding_hints.items()))
        from ..amp import amp_enabled, enable_amp
        from ..flags import get_flag
        check_nan = _flag_on("PADDLE_TPU_CHECK_NAN_INF")
        use_amp = self._force_bf16 if self._force_bf16 is not None \
            else amp_enabled()
        key = ("megastep", k, program, program._version, sig,
               fetch_names, state_keys, hints, check_nan, use_amp,
               get_flag("fuse_conv_bn"),
               tuple(sorted(static_info.items())))
        from .. import monitor as _mon
        mon_on = _mon.enabled()
        entry = self._cache.get(key)
        if entry is not None and mon_on:
            _mon.on_cache_hit()
        if entry is None:
            mega = self._exe._build_megastep(
                program, tuple(sorted(feeds_k)), fetch_names,
                state_keys, static_info, check_nan, k)

            def fn(state, feeds, keys, _fn=mega, _amp=use_amp):
                # pin AMP for the trace, restore after (see run())
                prev = amp_enabled()
                enable_amp(_amp)
                try:
                    return _fn(state, feeds, keys)
                finally:
                    enable_amp(prev)

            entry = jax.jit(fn, donate_argnums=(0,))
            self._cache[key] = entry
            if mon_on:
                import jax.numpy as _jnp
                rng0 = jax.vmap(jax.random.key)(
                    _jnp.zeros((k,), _jnp.uint32))
                _mon.on_compile(
                    program, key, key[4],
                    cost_fn=lambda: _step_costs_safe(
                        fn, dict(state), dict(feeds_k), rng0),
                    executor="pexe",
                    tokens=_mon.tokens_in_feeds(feeds_k),
                    devices=self.device_count)

        base = program.random_seed * 1000003 + self._exe._rng_counter
        self._exe._rng_counter += k
        import jax.numpy as jnp
        keys = jax.vmap(jax.random.key)(jnp.asarray(
            [np.uint32(base + i) for i in range(k)]))

        repl = NamedSharding(self.mesh, PartitionSpec())
        state_dev = {n: self._to_global(v, self._state_sharding(n))
                     for n, v in state.items()}
        dp_axis = None
        if "dp" in self.mesh.axis_names:
            dp_axis = "dp"
        # dim 0 is the scan dim: shard each step's batch (dim 1) on dp
        def feed_sharding(n, v):
            if n in lod_keys or getattr(v, "ndim", 0) < 2 \
                    or dp_axis is None:
                return repl
            return NamedSharding(self.mesh,
                                 PartitionSpec(None, dp_axis))

        feeds_dev = {n: self._to_global(v, feed_sharding(n, v))
                     for n, v in feeds_k.items()}

        window = max(1, int(get_flag("megastep_inflight")))
        inflight = self.__dict__.setdefault("_inflight", [])
        while len(inflight) >= window:
            jax.block_until_ready(inflight.pop(0))

        t0 = _time.perf_counter() if mon_on else 0.0
        if mon_on:
            timer = _mon.step_timer(self)
            do_sync = timer.begin(t0)
        fetches_k, new_state, guards_k, lods_k = entry(
            state_dev, feeds_dev, keys)
        if mon_on:
            fb = _mon.feed_nbytes(feeds_k)
            tk = _mon.tokens_in_feeds(feeds_k)
            if do_sync:
                jax.block_until_ready(fetches_k)
                _mon.on_megastep(
                    key, timer.end_synced(_time.perf_counter(), t0), k,
                    feed_bytes=fb, tokens=tk, executor="pexe")
            else:
                _mon.on_megastep(key, _time.perf_counter() - t0, k,
                                 feed_bytes=fb, tokens=tk,
                                 executor="pexe", synced=False)

        fetches_k = [self._local_value(v) for v in fetches_k]
        lods_k = {n: self._local_value(v) for n, v in lods_k.items()}
        guards_k = {n: self._local_value(v) for n, v in guards_k.items()}
        for n, v in new_state.items():
            scope.set(n, v)
        if check_nan:
            _Exe._check_guards_steps(guards_k, k)
        out = _Exe._split_step_fetches(fetch_names, fetches_k, lods_k,
                                       k, return_numpy)
        if check_nan:
            for fi in out:
                _Exe._check_nan_inf(fetch_names, fi)
        if not return_numpy:
            inflight.append(fetches_k)
        return out

    def _run_impl(self, fetch_list, feed=None, feed_dict=None,
                  return_numpy=True):
        feed = dict(feed or feed_dict or {})
        program = self._program
        scope = self._scope
        fetch_names = tuple(
            f.name if isinstance(f, Variable) else str(f)
            for f in (fetch_list or []))

        dp = 1
        if "dp" in self.mesh.axis_names:
            dp = self.mesh.shape["dp"]
        # ragged token buffers keep a replicated layout (their row count is
        # data-dependent); GSPMD re-shards downstream. _normalize_feeds also
        # buckets the flat LoD totals so signatures stay cache-stable.
        from ..core.executor import _normalize_feeds
        feed_arrays, static_info = _normalize_feeds(
            feed, accum_steps=self._accum_steps,
            plan_cache=self._feed_plans)
        if self._accum_steps > 1:
            self._check_accum_weights(feed_arrays)
        lod_keys = {k for k in feed_arrays
                    if k.endswith("@LOD") or k.endswith("@ACCUM_TOKENS")}
        lod_keys |= {k for k, v in feed.items() if isinstance(v, LoDTensor)}
        for k, v in feed_arrays.items():
            if k in lod_keys:
                continue
            if v.ndim >= 1 and v.shape[0] % dp != 0:
                raise ValueError(
                    "feed %r batch dim %d not divisible by dp=%d "
                    "(SplitLoDTensor parity requires equal chunks)"
                    % (k, v.shape[0], dp))

        persistable = [v.name for v in program.global_block().vars.values()
                       if v.persistable]
        state = {n: scope.find_var(n) for n in persistable
                 if scope.find_var(n) is not None}
        state_keys = tuple(sorted(state))

        hints = tuple(sorted(
            (k, tuple(v)) for k, v in program._sharding_hints.items()))
        from ..core.executor import _flag_on
        from ..amp import amp_enabled, enable_amp
        check_nan = _flag_on("PADDLE_TPU_CHECK_NAN_INF")
        use_amp = self._force_bf16 if self._force_bf16 is not None \
            else amp_enabled()
        from ..flags import get_flag
        key = (program, program._version, _feed_signature(feed_arrays),
               fetch_names, state_keys, hints, check_nan, use_amp,
               self._accum_steps, self._accum_loss_norm,
               get_flag("fuse_conv_bn"),
               tuple(sorted(static_info.items())))
        from .. import monitor as _mon
        mon_on = _mon.enabled()
        entry = self._cache.get(key)
        repl = NamedSharding(self.mesh, PartitionSpec())
        if entry is not None and mon_on:
            _mon.on_cache_hit()
        if entry is None:
            built = self._exe._build(program, tuple(sorted(feed_arrays)),
                                     fetch_names, state_keys,
                                     static_info=static_info,
                                     check_nan=check_nan,
                                     accum_steps=self._accum_steps,
                                     accum_loss_norm=self._accum_loss_norm)
            if mon_on:
                from ..core.executor import _step_costs_safe
                rng0 = jax.random.key(0)
                _mon.on_compile(
                    program, key, key[2],
                    cost_fn=lambda: _step_costs_safe(
                        built, dict(state), dict(feed_arrays), rng0),
                    executor="pexe",
                    tokens=_mon.tokens_in_feeds(feed_arrays),
                    devices=self.device_count)

            def fn(state, feeds, key, _fn=built, _amp=use_amp):
                # lowering reads the AMP flag at TRACE time; pin it for
                # the trace and restore the ambient value (no global leak)
                prev = amp_enabled()
                enable_amp(_amp)
                try:
                    return _fn(state, feeds, key)
                finally:
                    enable_amp(prev)

            # Shardings are established by COMMITTING the inputs (the
            # device_put/make_array calls below), not by in_shardings:
            # constraining the jit would force a reshard of step-2 state
            # (whose committed sharding is whatever step 1 produced),
            # which multi-process arrays cannot do. Committed-input
            # propagation is the standard JAX training-loop pattern and
            # keeps single- and multi-host behavior identical.
            entry = jax.jit(fn, donate_argnums=(0,))
            self._cache[key] = entry

        rng_key = jax.random.key(
            np.uint32(program.random_seed * 1000003
                      + self._exe._rng_counter))
        self._exe._rng_counter += 1

        # place state per its sharding once; jit keeps the placement
        # on subsequent steps (see _to_global)
        state_dev = {n: self._to_global(v, self._state_sharding(n))
                     for n, v in state.items()}
        data_sh = self._data_sharding()
        feeds_dev = {k: self._to_global(v, repl if k in lod_keys
                                        else data_sh)
                     for k, v in feed_arrays.items()}

        import time as _time
        t0 = _time.perf_counter() if mon_on else 0.0
        if mon_on:
            # windowed sync (monitor_sync_every) — shared StepTimer,
            # same windowing as core Executor.run
            timer = _mon.step_timer(self)
            do_sync = timer.begin(t0)
        fetches, new_state, guards, fetch_lods = entry(
            state_dev, feeds_dev, rng_key)
        if mon_on:
            fb = _mon.feed_nbytes(feed_arrays)
            tk = _mon.tokens_in_feeds(feed_arrays)
            if do_sync:
                jax.block_until_ready(fetches)   # honest step latency
                _mon.on_step(key,
                             timer.end_synced(_time.perf_counter(), t0),
                             feed_bytes=fb, tokens=tk, executor="pexe")
            else:
                _mon.on_step(key, _time.perf_counter() - t0,
                             feed_bytes=fb, tokens=tk, executor="pexe",
                             synced=False)

        fetches = [self._local_value(v) for v in fetches]
        fetch_lods = {k: self._local_value(v)
                      for k, v in fetch_lods.items()}
        guards = {k: self._local_value(v) for k, v in guards.items()}
        fetches = Executor._trim_fetches(fetch_names, fetches, fetch_lods)
        for n, v in new_state.items():
            scope.set(n, v)
        if check_nan:
            Executor._check_guards(guards)
            Executor._check_nan_inf(fetch_names, fetches)
        if return_numpy:
            return [as_numpy(v) for v in fetches]
        return list(fetches)
