"""paddle_tpu.resilience — fault injection + self-healing runtime.

The reference framework's whole cloud story is fault tolerance: the Go
master's at-least-once task leases (go/master/service.go) and the
pserver's CRC'd checkpoint/recover path (go/pserver/service.go). The
ports of those pieces (distributed/master.py, rpc.py, membership.py,
io.save_checkpoint) each survive a crash individually; this package is
what COMPOSES them — and what proves the composition under injected
failure.

Three pieces (see each module's docstring):
  faults   deterministic, seeded fault-injection plan: RPC frame
           drop / delay / close-mid-frame / duplicate, pserver/master
           kill-switches, checkpoint corruption, one-shot NaN batches
  retry    bounded-exponential-backoff Policy used by RPCClient /
           MasterClient to transparently reconnect and re-issue
           idempotent verbs (incarnation/replacement-aware via an
           endpoint resolver)
  driver   ``resilient_loop``: background checkpointing off the step
           path, auto-resume from the newest valid checkpoint, and a
           NaN/Inf guard that rolls back and skips the poisoned batch

Arm a fault plan for a whole process with ``PADDLE_TPU_FAULTS`` (JSON
spec or ``@/path/to/plan.json``) + ``PADDLE_TPU_FAULTS_SEED``, or
programmatically::

    from paddle_tpu.resilience import faults
    plan = faults.arm({"rpc": {"drop": 0.02}, "nan": {"step": 7}}, seed=1)
    ...
    faults.disarm()

Every injection, retry, reconnect, rollback, and resume lands in
paddle_tpu.monitor (counters always; flight-recorder events when a
recorder is armed), so a chaos run leaves a machine-readable black box.
"""

from . import faults  # noqa: F401
from . import retry  # noqa: F401
from . import driver  # noqa: F401
from .retry import Policy, default_policy  # noqa: F401
from .driver import resilient_loop  # noqa: F401

__all__ = ["faults", "retry", "driver", "Policy", "default_policy",
           "resilient_loop"]
