"""Serving fleet: self-healing multi-replica router with exactly-once
decode under churn.

ROADMAP direction 2 composed: every fleet primitive the runtime already
has — membership's TTL-lease KV (the replica registry), resilience's
retry ``Policy`` + seeded fault injection, the frame protocol of
``distributed/rpc.py`` (so faults / trace context / retries ride along
for free), trace spans and the SLO error budget — put in front of N
``serving.Engine`` replicas the way production serving systems put a
fault-tolerant front door ahead of iteration-level schedulers (Orca,
OSDI '22) and treat replica churn as steady state (Borg, EuroSys '15).

Topology::

                    submit()/result()
                          |
                       Router ————— lease registry (KVServer, role
                      /  |  \\            '/replica/<slot>')
                SUBM /   |   \\ POLL+CANC      |
                    /    |    \\          Supervisor (respawns dead /
             ReplicaServer x N            evicted slots via factory)
                 |  journal (dedup by id)
              Engine (continuous batching, greedy decode)

Verbs (length-prefixed frames, same wire as the pserver/master/KV
tiers — an armed fault plan, tracer, or retry policy hooks them with
zero new plumbing):

    SUBM  name=<rid>  {prompt, max_new,   admit once (journal dedups a
                       sampling?}         retried/duplicated id);
                                          sampling = SamplingParams
                                          dict (ISSUE 10) — carried in
                                          the journal, so resubmission
                                          re-executes with the SAME
                                          temperature/top-k/top-p/seed
                                          and stays deterministic
    POLL  {wait, max}                     long-poll finished-but-unacked
                                          results (at-least-once
                                          delivery; re-polled until
                                          acked)
    CANC  name=<rid>                      ack/forget a delivered result
                                          (idempotent)
    STAT                                  replica load/health snapshot
    METR / HLTH                           fleet telemetry scrape
                                          (monitor/collector.py):
                                          metrics-registry snapshot +
                                          recorder delta / liveness
    CLKS / EXIT                           clock probe / shutdown

Exactly-once contract: the Router assigns each accepted request a
durable id and journals it; dispatch is at-least-once (resubmission on
replica lease expiry, watchdog stall-eviction, or verb failure past the
retry deadline), delivery is at-least-once (results stay in the replica
journal until acked), and BOTH are deduped by id — the replica journal
dedups admission, the router journal dedups completion, so a
slow-but-alive replica's late result cannot double-complete a request
that a survivor re-executed. Greedy decode determinism makes the
re-execution token-identical, which is what lets the chaos gate
(tests/test_fleet.py) pin "kill a replica mid-traffic → every accepted
request completes exactly once, token-identical to the fault-free run".

Backpressure and load shedding: dispatch respects a bounded per-replica
in-flight window (``serving_fleet_window``); requests beyond it queue
router-side. Once the global queue bound (``serving_fleet_queue``) is
hit, ``submit`` fast-fails with the typed ``Overloaded`` error, counted
against the SLO error budget (a ``serving_request`` row with the error
lands under the router's engine label).

Telemetry: ``ptpu_fleet_{replicas,requests,resubmissions,shed,
evictions,duplicate_results}_*`` metrics plus the live
``ptpu_fleet_queue_depth`` gauge (the standing dispatch-queue depth
monitor.signals' queue-pressure rule reads); ``router.dispatch`` spans
(rid / slot / endpoint attrs — a resubmitted id shows two dispatch
spans with different endpoints, the resubmission hop ``trace merge``
renders) nesting the ``fleet.subm`` client verb span whose context
propagates into the replica's ``replica.SUBM`` server span; engine-side
request rows/spans carry the durable id (``Engine.submit(request_id=)``)
so the fleet's per-replica logs union into one SLO verdict
(``python -m paddle_tpu.slo spec.json --log replica0.jsonl
replica1.jsonl ...``).
"""

import collections
import itertools
import json
import threading
import time
import uuid
import zlib

from ..distributed import membership as _membership
from ..distributed.membership import KVClient
from ..distributed.rpc import (_send_msg, _recv_msg, _clock_reply,
                               _metr_reply, _hlth_reply, _dump_reply)
from ..monitor import metrics as _metrics
from ..monitor import runtime as _monrt
from ..resilience import faults as _faults
from ..resilience.retry import Policy, RETRYABLE
from ..trace import runtime as _trace
from .engine import Engine, _flag

__all__ = ["Overloaded", "ReplicaDraining", "ReplicaServer", "Replica",
           "ReplicaClient", "Router", "FleetRequest", "Supervisor",
           "choose_replica", "REPLICA_ROLE", "CANDIDATE_ROLE",
           "EVICTED_PREFIX", "DRAINING_PREFIX", "VERSION_PREFIX"]

REPLICA_ROLE = "replica"
# Candidate replicas (canary analysis plane, ISSUE 19) register under
# their OWN role so the incumbent registry, its Supervisor and the
# collector's default discovery never see them; the router resolves
# the role only while a mirror is armed and keys candidate slots at
# _CAND_BASE + <registry slot> so one journal/poller/dedup machinery
# serves both populations (exactly-once holds across the split).
CANDIDATE_ROLE = "candidate"
_CAND_BASE = 1 << 20
# Stall-evicted slots are TOMBSTONED (CAS endpoint -> marker) rather
# than deleted: a delete would let the wedged holder's lease thread
# reclaim the slot with its create-if-absent CAS, while a changed value
# makes its next expect-guarded keepalive FAIL -> `lost` -> it stops
# serving a slot it no longer holds (membership's split-brain guard,
# reused as the eviction mechanism). The marker itself is
# registry-level protocol shared with every registry reader (the
# monitor collector filters it during discovery), so it lives in
# membership; re-exported here for the existing fleet API surface.
EVICTED_PREFIX = _membership.EVICTED_PREFIX
# Graceful-drain lease mark (ISSUE 18): the retiring holder re-marks
# its OWN lease value to "draining:<ep>" — the lease stays alive, the
# router keeps polling the endpoint for in-flight results but stops
# dispatching new work there. Registry-level protocol like
# EVICTED_PREFIX; lives in membership, re-exported here.
DRAINING_PREFIX = _membership.DRAINING_PREFIX
# Canary lease mark (ISSUE 19): a candidate replica's lease value is
# "version:<ver>:<ep>" so the registry itself carries the version the
# endpoint serves; the router strips it during candidate resolution
# and stamps the version on canary dispatch spans.
VERSION_PREFIX = _membership.VERSION_PREFIX

_REG = _metrics.registry()
FLEET_REPLICAS = _REG.gauge(
    "ptpu_fleet_replicas",
    "live serving replicas resolved from the lease registry", ("router",))
FLEET_REQUESTS = _REG.counter(
    "ptpu_fleet_requests_total", "requests accepted by the router",
    ("router",))
FLEET_RESUBMISSIONS = _REG.counter(
    "ptpu_fleet_resubmissions_total",
    "journaled requests re-submitted to a survivor after replica "
    "death/eviction", ("router",))
FLEET_SHED = _REG.counter(
    "ptpu_fleet_shed_total",
    "requests fast-failed (Overloaded) at the global queue bound",
    ("router",))
# live pressure gauge (ISSUE 14): the shed counter records the DROPS,
# this gauge the router's standing dispatch-queue depth — the
# queue-pressure input monitor.signals' sustained rules and the
# direction-2 autoscaling scale_hint() read (previously counters-only,
# so "is the queue deep RIGHT NOW" was not scrapeable)
FLEET_QUEUE_DEPTH = _REG.gauge(
    "ptpu_fleet_queue_depth",
    "requests waiting in the router's dispatch queue", ("router",))
FLEET_EVICTIONS = _REG.counter(
    "ptpu_fleet_evictions_total",
    "replicas evicted from dispatch", ("reason",))
FLEET_DUPLICATES = _REG.counter(
    "ptpu_fleet_duplicate_results_total",
    "late results for already-completed ids, deduped by the journal",
    ("router",))
# canary analysis plane (ISSUE 19): mirrored = shadow duplicates
# dispatched (scored, never served), dropped = duplicates abandoned
# best-effort (candidate dead/overloaded/timed out — NEVER affects the
# served request), canary = requests the weighted split sent to a
# candidate for real
FLEET_MIRRORED = _REG.counter(
    "ptpu_fleet_mirrored_total",
    "requests duplicated to shadow candidate replicas", ("router",))
FLEET_MIRROR_DROPPED = _REG.counter(
    "ptpu_fleet_mirror_dropped_total",
    "shadow duplicates abandoned without a joined pair", ("router",))
FLEET_CANARY = _REG.counter(
    "ptpu_fleet_canary_total",
    "requests served FOR REAL by canary candidate replicas",
    ("router",))


class Overloaded(RuntimeError):
    """Typed load-shed error: the router's global queue bound is hit.
    Raised synchronously from ``submit`` (fast-fail — the caller can
    back off / retry elsewhere) and counted against the SLO error
    budget."""

    def __init__(self, queued, bound):
        super().__init__(
            "router overloaded: %d queued >= global bound %d"
            % (queued, bound))
        self.queued = queued
        self.bound = bound


class ReplicaDraining(RuntimeError):
    """Typed SUBM NACK ("DRNG" reply) from a gracefully draining
    replica: admissions are closed while in-flight work retires and
    POLL/CANC keep serving. NOT retryable wire-level (the replica is
    healthy — retrying the same endpoint is pointless) and NOT a
    request failure: the router requeues the request for another
    replica without burning its attempt budget."""

    def __init__(self, rid):
        super().__init__("replica draining: %s not admitted" % rid)
        self.rid = rid


# -- replica side -----------------------------------------------------------

class ReplicaServer:
    """RPC front of ONE Engine replica (SUBM/POLL/CANC/STAT on the
    rpc.py frame protocol). The journal makes admission idempotent
    (exactly-once per id even when the router's at-least-once dispatch
    retries or a fault duplicates the frame) and keeps finished results
    until the router acks them with CANC (at-least-once delivery).

    Fault sites (armed plan): ``kill`` target ``replica`` /
    ``replica:<slot>`` hard-crashes the server exactly like the pserver
    kill-switch; ``stall`` wedges EVERY dispatch for its duration —
    the lease keeps beating (the 'process' is alive), so only the
    router's response-deadline watchdog can evict it."""

    _PRUNE_S = 120.0

    def __init__(self, engine, host="127.0.0.1", port=0, slot=None,
                 on_crash=None):
        import socketserver
        self.engine = engine
        self.slot = slot
        self.version = None        # serving artifact version (ISSUE 18)
        self.kill_role = "replica"  # chaos-kill target role; Replica
        # rebinds it ("candidate") so plans can crash canary cells only
        self._on_crash = on_crash
        self._draining = False     # drain state: NACK new SUBM, keep
        self._lock = threading.Lock()  # POLL/CANC/STAT serving
        self._fin_cv = threading.Condition(self._lock)
        self._jobs = {}            # rid -> {"req": Request, "t0": ts}
        self._accepted = 0         # SUBMs admitted (fault thresholds)
        self._stall_until = 0.0
        # event-driven delivery: the engine's completion hook wakes
        # long-polling handlers the moment a future resolves, so the
        # router sees a result one RPC round trip after retirement
        # instead of a poll-granularity later
        engine.on_retire = self._on_engine_retire
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    while True:
                        op, name, payload, tctx = _recv_msg(
                            self.request, want_ctx=True)
                        trc = _trace._TRACER
                        if trc is not None and tctx is not None \
                                and op != "CLKS":
                            with trc.server_span("replica." + op, tctx,
                                                 op=op, rid=name):
                                outer._dispatch(self.request, op, name,
                                                payload)
                        else:
                            outer._dispatch(self.request, op, name,
                                            payload)
                        if op == "EXIT":
                            break
                except (ConnectionError, OSError):
                    pass

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.port = self._server.server_address[1]
        self.endpoint = "%s:%d" % (host, self.port)
        trc = _trace._TRACER
        if trc is not None:
            trc.record_server_port(self.port, self.endpoint)
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        if self._thread.is_alive():
            self._server.shutdown()
        self._server.server_close()

    def drain(self):
        """Close admissions (new SUBM gets the typed DRNG NACK, which
        the router re-dispatches elsewhere) while POLL/CANC/STAT keep
        serving so in-flight work retires and is acked. One-way: a
        draining server never re-admits — the cell retires next."""
        self._draining = True

    # ------------------------------------------------------------------
    def _maybe_fault(self):
        plan = _faults._ACTIVE
        if plan is None:
            return
        targets = [self.kill_role]
        if self.slot is not None:
            targets.append("%s:%d" % (self.kill_role, self.slot))
        v = self._accepted
        for t in targets:
            if plan.should_kill(t, v):
                # hard crash: no reply for the in-flight request; the
                # cell's on_crash models the whole process dying (lease
                # thread included). stop() must run off-thread —
                # shutdown() handshakes with serve_forever.
                cb = self._on_crash or self.stop
                threading.Thread(target=cb, daemon=True).start()
                raise ConnectionError("injected fault: replica killed")
            secs = plan.should_stall(t, v)
            if secs:
                self._stall_until = max(self._stall_until,
                                        time.monotonic() + secs)
        until = self._stall_until
        now = time.monotonic()
        if until > now:
            # wedge: EVERY handler thread that reaches dispatch sleeps
            # out the stall — the replica stops answering while its
            # engine thread (deliberately untouched) keeps decoding,
            # which is exactly the slow-but-alive shape whose late
            # results the router journal must dedup
            time.sleep(until - now)

    def _on_engine_retire(self, req):
        with self._fin_cv:
            self._fin_cv.notify_all()

    def _collect_done_locked(self, cap):
        done = []
        for rid, job in self._jobs.items():
            req = job["req"]
            if not req.done():
                continue
            if req._error is not None:
                done.append({"id": rid, "error": repr(req._error)})
            else:
                ent = {"id": rid, "tokens": list(req.tokens),
                       "score": req.score}
                ver = getattr(req, "versions", None)
                if ver is not None:
                    # scoring result: the served cache-version
                    # coordinates travel with the score so the caller
                    # can check its pin (determinism contract)
                    ent["versions"] = ver
                done.append(ent)
            if len(done) >= cap:
                break
        return done

    def _prune_locked(self, now):
        dead = [rid for rid, j in self._jobs.items()
                if j["req"].done() and now - j["t0"] > self._PRUNE_S]
        for rid in dead:                 # router gone: never acked
            del self._jobs[rid]

    def _dispatch(self, sock, op, name, payload):
        self._maybe_fault()
        if op == "SUBM":
            body = json.loads(bytes(payload).decode())
            bad = None
            drng = False
            with self._lock:
                self._prune_locked(time.time())
                if self._draining and name not in self._jobs:
                    # drain NACK: no NEW admissions (a duplicate SUBM
                    # for an already-journaled id still acks OK — the
                    # dedup contract holds through the drain). Sent
                    # below, after the lock (lock-discipline).
                    drng = True
                elif name not in self._jobs:
                    try:
                        if "features" in body:
                            # scoring payload (serving.sparse): the
                            # replica fronts a ScoringEngine — same
                            # journal/dedup/delivery machinery, the
                            # score rides the result wire's "score"
                            # with empty tokens
                            req = self.engine.submit(
                                body["features"], request_id=name,
                                version_pin=body.get("version_pin"))
                        else:
                            req = self.engine.submit(
                                body["prompt"], body["max_new"],
                                request_id=name,
                                sampling=body.get("sampling"))
                    except (ValueError, TypeError) as e:
                        # invalid request (e.g. prompt + max_new past
                        # the model's max_len — ValueError) or a
                        # WORKLOAD mismatch (TypeError: a scoring
                        # payload reaching a decode engine in a fleet
                        # that mixed replica kinds under one role):
                        # a typed reply — NOT a torn connection — so
                        # the router fails it terminally instead of
                        # retrying it into every replica in turn.
                        # Sent below, after the lock: a slow reader
                        # must not stall the other handler threads
                        # (analysis --runtime, lock-discipline).
                        bad = repr(e).encode()
                    except RuntimeError as e:
                        # engine closed (replica dying): tear the
                        # connection — the router retries elsewhere
                        raise ConnectionError(
                            "replica engine unavailable: %s" % e)
                    else:
                        self._jobs[name] = {"req": req,
                                            "t0": time.time()}
                        self._accepted += 1
            if drng:
                _send_msg(sock, "DRNG", name)
                return
            if bad is not None:
                _send_msg(sock, "BADR", name, bad)
                return
            _send_msg(sock, "OK", name)
        elif op == "POLL":
            body = json.loads(bytes(payload).decode()) if payload else {}
            wait = min(float(body.get("wait", 0.0)), 5.0)
            cap = int(body.get("max", 16))
            deadline = time.monotonic() + wait
            with self._fin_cv:
                while True:
                    done = self._collect_done_locked(cap)
                    remaining = deadline - time.monotonic()
                    if done or remaining <= 0:
                        break
                    # woken by the engine's on_retire hook the moment
                    # a future resolves (event-driven, not a scan)
                    self._fin_cv.wait(remaining)
            _send_msg(sock, "VAL", "",
                      json.dumps({"done": done}).encode())
        elif op == "CANC":
            # name: one rid, or a comma-joined batch (the router acks
            # a whole POLL delivery in ONE round trip)
            with self._lock:
                for rid in name.split(","):
                    self._jobs.pop(rid, None)
            _send_msg(sock, "OK", name)
        elif op == "STAT":
            with self._lock:
                inflight = sum(1 for j in self._jobs.values()
                               if not j["req"].done())
                unacked = len(self._jobs)
            st = self.engine.stats
            _send_msg(sock, "VAL", "", json.dumps({
                "slot": self.slot, "inflight": inflight,
                "unacked": unacked, "slots": self.engine.slots,
                "steps": st["steps"], "tokens": st["tokens"],
                "admissions": st["admissions"],
                "version": self.version,
                "draining": self._draining}).encode())
        elif op == "CLKS":
            _clock_reply(sock)
        elif op == "METR":
            # fleet telemetry scrape — deliberately BEHIND _maybe_fault
            # like every other verb: a wedged replica that stops
            # answering METR is exactly the staleness a collector must
            # see, not paper over
            _metr_reply(sock, payload, role="replica")
        elif op == "HLTH":
            _hlth_reply(sock, role="replica")
        elif op == "DUMP":
            # black-box capture — also behind _maybe_fault: a wedged
            # replica is dropped by the coordinator's deadline, which
            # is itself forensic signal (the bundle records who failed
            # to answer)
            with self._lock:
                inflight = sum(1 for j in self._jobs.values()
                               if not j["req"].done())
                unacked = len(self._jobs)
            st = self.engine.stats
            _dump_reply(sock, payload, role="replica", state={
                "slot": self.slot, "inflight": inflight,
                "unacked": unacked, "slots": self.engine.slots,
                "steps": st["steps"], "tokens": st["tokens"],
                "admissions": st["admissions"],
                "version": self.version,
                "draining": self._draining})
        elif op == "EXIT":
            _send_msg(sock, "OK")
            self.stop()
        else:
            _send_msg(sock, "ERR", "unknown op %s" % op)


class Replica:
    """One serving replica 'process': Engine + ReplicaServer + a TTL
    lease in the role registry (membership.register_endpoint). The
    Supervisor replaces the whole cell on death/eviction; a replacement
    built from the same model weights (shared object in-process, or a
    checkpoint in a real deployment) re-executes resubmitted requests
    token-identically — greedy decode is deterministic."""

    def __init__(self, kv, model, desired, slots=2, ttl=0.5,
                 role=REPLICA_ROLE, name=None, engine_factory=None,
                 version=None, shadow=False, **engine_kwargs):
        self.name = name or ("replica-" + uuid.uuid4().hex[:6])
        # serving artifact version (ISSUE 18 rolling updates): explicit,
        # or derived from the artifact directory name when cold-booting
        # from a PR-15 inference artifact — stamped into STAT/DUMP and
        # the fleet's version-mix telemetry
        if version is None and isinstance(model, str):
            import os
            version = os.path.basename(os.path.normpath(model))
        self.version = version
        self.shadow = bool(shadow)
        if engine_factory is not None:
            # non-decode cells (serving.sparse ScoringEngine): the
            # factory builds anything speaking the Engine protocol
            # (submit/close/stats/slots/on_retire) — the RPC front,
            # lease, journal and router machinery are workload-blind
            self.engine = engine_factory(self.name)
        else:
            self.engine = Engine(model, slots=slots, name=self.name,
                                 **engine_kwargs)
        # canary analysis plane (ISSUE 19): shadow cells mark every
        # engine row/metric as mirrored (excluded from the incumbent
        # SLO surface); the version rides serving_request rows either
        # way so delta objectives can split samples by version
        try:
            self.engine.shadow = self.shadow
            self.engine.version = self.version
        except AttributeError:
            pass               # slotted factory engines: rows unmarked
        self.server = ReplicaServer(self.engine, on_crash=self.crash)
        self.endpoint = self.server.endpoint
        try:
            self.slot, self.lease = _membership.register_endpoint(
                kv, role, desired, self.endpoint, ttl=ttl)
        except Exception:
            # no free slot (registration raced/timed out): a half-built
            # cell must not leak its decode thread and listening socket
            # — the Supervisor retries with a fresh cell next tick
            try:
                self.server.stop()
            except OSError:
                pass
            self.engine.close()
            raise
        self.server.slot = self.slot
        self.server.version = self.version
        # fault kill-switches address cells by role ("candidate" /
        # "candidate:<slot>" vs the default "replica"), so a chaos plan
        # can kill a candidate mid-shadow without touching incumbents
        self.server.kill_role = role
        if role == CANDIDATE_ROLE and self.version:
            # stamp the version on the lease (registry-level canary
            # protocol): readers see which artifact the endpoint
            # serves. Best-effort — STAT still reports the version.
            try:
                self.lease.mark("%s%s:%s" % (VERSION_PREFIX,
                                             self.version,
                                             self.endpoint))
            except (ConnectionError, OSError):
                pass
        self.server.start()

    def drain(self):
        """Begin a graceful drain: close admissions on the server (new
        SUBM → typed DRNG NACK the router re-dispatches) and re-mark
        the lease value to ``draining:<ep>`` so every registry reader
        sees the state. The lease keeps beating — the router must keep
        polling for in-flight results until they are delivered and
        acked; the caller retires the cell (``shutdown``) once STAT
        reports inflight == 0 and unacked == 0."""
        self.server.drain()
        try:
            self.lease.mark(DRAINING_PREFIX + self.endpoint)
        except (ConnectionError, OSError):
            pass                 # KV unreachable: DRNG NACKs still gate

    def crash(self):
        """The injected-kill path: the whole 'process' dies — server,
        lease heartbeat, engine. In-flight engine requests fail with
        attribution (their rows carry the error; the router re-executes
        them on a survivor)."""
        self.lease._stop.set()
        try:
            self.server.stop()
        except OSError:
            pass
        self.engine.close()

    def shutdown(self):
        """Graceful leave: revoke the lease first so the router stops
        routing here before the endpoint disappears."""
        try:
            self.lease.revoke()
        except (ConnectionError, OSError):
            pass
        try:
            self.server.stop()
        except OSError:
            pass
        self.engine.close()


# -- router side ------------------------------------------------------------

class ReplicaClient:
    """Router-side client for one replica endpoint. EVERY verb is
    idempotent by construction — SUBM dedups by id in the replica
    journal, POLL/STAT are reads, CANC re-acks — so all of them may run
    under a retry ``Policy`` (reconnect + re-issue on socket errors),
    and the policy's total deadline doubles as the stall watchdog: a
    wedged replica that answers nothing for the whole deadline is
    reported to the router as down."""

    def __init__(self, endpoint, timeout=2.0, retry=None):
        host, port = endpoint.rsplit(":", 1)
        self._addr = (host, int(port))
        self._timeout = float(timeout)
        self._retry = retry
        self._sock = None

    def _connect(self):
        import socket
        s = socket.create_connection(self._addr, timeout=self._timeout)
        s.settimeout(self._timeout)
        self._sock = s
        if _trace._TRACER is not None:
            _trace.annotate(endpoint="%s:%d" % self._addr)

    def _drop_conn(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _call(self, what, body):
        trc = _trace._TRACER
        if trc is None:
            return self._call_inner(what, body)
        with trc.span(what, endpoint="%s:%d" % self._addr):
            return self._call_inner(what, body)

    def _call_inner(self, what, body):
        if self._retry is None:
            if self._sock is None:
                self._connect()
            return body()

        def attempt():
            if self._sock is None:
                self._connect()
                _monrt.on_reconnect("fleet")
                _trace.annotate(reconnected=True)
            return body()

        return self._retry.run(
            attempt, what=what, retry_on=RETRYABLE,
            on_retry=lambda a, e: self._drop_conn())

    def submit(self, rid, prompt, max_new, sampling=None,
               features=None, version_pin=None):
        def body():
            if features is not None:
                # scoring payload (serving.sparse ScoringEngine)
                wire = {"features": features}
                if version_pin is not None:
                    wire["version_pin"] = version_pin
            else:
                wire = {"prompt": [int(t) for t in prompt],
                        "max_new": int(max_new)}
                if sampling is not None:
                    wire["sampling"] = sampling
            _send_msg(self._sock, "SUBM", rid, json.dumps(wire).encode())
            op, _, payload = _recv_msg(self._sock)
            if op == "BADR":
                # typed rejection: not retryable — the request itself
                # is invalid for the model, on any replica
                raise ValueError("replica rejected %s: %s"
                                 % (rid, bytes(payload).decode()))
            if op == "DRNG":
                # typed drain NACK: healthy replica, closed admissions
                # — the router re-dispatches elsewhere (no retry here:
                # this endpoint will keep refusing)
                raise ReplicaDraining(rid)
            if op != "OK":
                raise ConnectionError("SUBM reply %s" % op)
        return self._call("fleet.subm", body)

    def poll(self, wait=0.0, max_results=16):
        """Finished-but-unacked results: list of ``{"id", "tokens",
        "score"}`` (or ``{"id", "error"}``) dicts, possibly empty."""
        def body():
            # the reply legitimately takes up to `wait` (server-side
            # long-poll) + handling; widen the recv window for this
            # call only
            self._sock.settimeout(self._timeout + wait)
            try:
                _send_msg(self._sock, "POLL", "", json.dumps(
                    {"wait": wait, "max": max_results}).encode())
                op, _, payload = _recv_msg(self._sock)
            finally:
                if self._sock is not None:
                    try:
                        self._sock.settimeout(self._timeout)
                    except OSError:
                        pass
            if op != "VAL":
                raise ConnectionError("POLL reply %s" % op)
            return json.loads(bytes(payload).decode())["done"]
        return self._call("fleet.poll", body)

    def cancel(self, rids):
        """Ack one rid or a batch (sequence) in a single round trip."""
        wire = rids if isinstance(rids, str) else ",".join(rids)

        def body():
            _send_msg(self._sock, "CANC", wire)
            op, _, _ = _recv_msg(self._sock)
            if op != "OK":
                raise ConnectionError("CANC reply %s" % op)
        return self._call("fleet.canc", body)

    def stat(self):
        def body():
            _send_msg(self._sock, "STAT")
            op, _, payload = _recv_msg(self._sock)
            if op != "VAL":
                raise ConnectionError("STAT reply %s" % op)
            return json.loads(bytes(payload).decode())
        return self._call("fleet.stat", body)

    def close(self):
        self._drop_conn()


class FleetRequest:
    """Router-side result handle (the fleet analog of serving.Request):
    ``result()`` blocks until a replica's result is delivered exactly
    once, or the request fails terminally (Overloaded is raised at
    submit time instead — shed requests never get a handle)."""

    __slots__ = ("rid", "prompt", "max_new", "session", "sampling",
                 "features", "versions", "tokens", "score",
                 "resubmits", "t_submit", "t_done", "_event", "_error")

    def __init__(self, rid, prompt, max_new, session=None,
                 sampling=None, features=None):
        self.rid = rid
        self.prompt = list(prompt)
        self.max_new = int(max_new)
        self.session = session
        self.sampling = sampling
        self.features = features   # scoring payload (serving.sparse)
        self.versions = None       # served cache version (scoring)
        self.tokens = None
        self.score = None
        self.resubmits = 0
        self.t_submit = time.perf_counter()
        self.t_done = None
        self._event = threading.Event()
        self._error = None

    def done(self):
        return self._event.is_set()

    def latency(self):
        if self.t_done is None:
            return None
        return self.t_done - self.t_submit

    def result(self, timeout=None):
        if not self._event.wait(timeout):
            raise TimeoutError(
                "fleet request %s not finished within %r s"
                % (self.rid, timeout))
        if self._error is not None:
            raise RuntimeError("fleet request %s failed: %r"
                               % (self.rid, self._error))
        return list(self.tokens), self.score


def choose_replica(loads, window, session=None, affinity=None):
    """PURE dispatch decision (the table-driven-test surface).

    loads:    {replica_slot: current in-flight count} for LIVE replicas
    window:   bounded per-replica in-flight cap (backpressure)
    session:  optional affinity key; affinity: {session: slot}

    Returns the chosen slot, or None when every replica is at its
    window (the request stays queued router-side). Session affinity
    wins while its replica is live and under the window; otherwise
    least-loaded, ties broken by the LOWEST slot index (deterministic)."""
    if session is not None and affinity is not None:
        slot = affinity.get(session)
        if slot in loads and loads[slot] < window:
            return slot
    cands = [(load, slot) for slot, load in loads.items()
             if load < window]
    if not cands:
        return None
    return min(cands)[1]


_QUEUED, _INFLIGHT, _DONE, _FAILED = "queued", "inflight", "done", \
    "failed"


def _strip_marks(val):
    """Strip lease-value marks: ``draining:``/``version:<ver>:`` ->
    (version | None, endpoint)."""
    ver = None
    if val.startswith(DRAINING_PREFIX):
        val = val[len(DRAINING_PREFIX):]
    if val.startswith(VERSION_PREFIX):
        ver, val = val[len(VERSION_PREFIX):].split(":", 1)
    return ver, val

# Completed/failed journal entries are retained this long for
# late-duplicate dedup (the slow-replica window), then pruned — the
# router journal must not grow with total traffic served. Session
# affinity is an LRU capped at _AFFINITY_MAX keys.
_JOURNAL_KEEP_S = 300.0
_JOURNAL_SWEEP_EVERY = 256
_AFFINITY_MAX = 8192


class Router:
    """The fleet front door: resolves live replicas from the lease
    registry, dispatches least-loaded with session affinity under a
    bounded per-replica window, journals accepted requests, and
    re-submits unfinished work to a survivor on replica lease expiry /
    stall eviction / verb failure — deduped by durable id, so
    completion is exactly-once (module docstring has the full
    contract)."""

    def __init__(self, kv_endpoint, role=REPLICA_ROLE, retry=None,
                 window=None, max_queue=None, stall_timeout=None,
                 poll_wait=0.2, refresh_interval=0.1, name="router",
                 max_attempts=5, client_timeout=1.0):
        self.name = name
        self.role = role
        self._window = int(window if window is not None
                           else _flag("serving_fleet_window", 8))
        self._max_queue = int(max_queue if max_queue is not None
                              else _flag("serving_fleet_queue", 64))
        self._stall_timeout = float(
            stall_timeout if stall_timeout is not None
            else _flag("serving_fleet_stall_timeout", 2.0))
        self._poll_wait = float(poll_wait)
        self._refresh = float(refresh_interval)
        self._client_timeout = float(client_timeout)
        self._max_attempts = int(max_attempts)
        # verbs run under a deadline-governed policy: the deadline IS
        # the stall watchdog threshold — a replica that answers nothing
        # for the whole budget is evicted, while transient frame faults
        # (drops/tears under an armed plan) are retried away inside it
        self._retry = retry if retry is not None else Policy(
            max_attempts=100, base_delay=0.02, max_delay=0.25,
            deadline=self._stall_timeout, seed=7)
        self._kv = KVClient(kv_endpoint)
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._journal = {}       # rid -> entry dict
        self._queue = collections.deque()    # rids awaiting dispatch
        self._replicas = {}      # slot -> {"endpoint","client"}
        self._inflight = {}      # slot -> set(rid)
        self._draining = set()   # slots closed to NEW dispatch (polled
        #                          for in-flight results until retired)
        self._affinity = collections.OrderedDict()  # session -> slot
        self._seq = itertools.count()
        self._submits_since_sweep = 0
        self._id = uuid.uuid4().hex[:8]
        self._stop = threading.Event()
        self._closed = False
        # canary analysis plane (ISSUE 19): one armed mirror at a time
        # — {"mode": "shadow"|"canary", "version", "fraction"}.
        # Shadow duplicates a sampled fraction to candidate slots
        # (scored, never served); canary routes the sampled fraction
        # there FOR REAL. Candidate slots live in self._replicas under
        # _CAND_BASE-offset keys; shadow copies are tracked in their
        # own inflight map (never the journal's) so a dropped mirror
        # can never requeue into the serving path.
        self._mirror = None
        self._cand_versions = {}     # offset slot -> artifact version
        self._mirror_queue = collections.deque()  # rids to duplicate
        self._mirror_inflight = {}   # offset slot -> set(rid)
        self._mirror_jobs = {}       # rid -> {"t0","inc","cand","version"}
        self._mirror_timeout = max(30.0, 10 * self._stall_timeout)
        # instance counters (authoritative for tests; the global
        # ptpu_fleet_* metrics mirror them)
        self.stats = {"requests": 0, "completed": 0, "shed": 0,
                      "resubmissions": 0, "duplicates": 0,
                      "evictions": {}, "failed": 0, "drain_nacks": 0,
                      "mirrored": 0, "mirror_pairs": 0,
                      "mirror_dropped": 0, "canary": 0,
                      "canary_served": 0}
        self._threads = [
            threading.Thread(target=self._registry_loop, daemon=True,
                             name="ptpu-%s-registry" % name),
            threading.Thread(target=self._dispatch_loop, daemon=True,
                             name="ptpu-%s-dispatch" % name),
            threading.Thread(target=self._mirror_loop, daemon=True,
                             name="ptpu-%s-mirror" % name),
        ]
        self._pollers = {}       # slot -> thread
        for t in self._threads:
            t.start()

    # -- public API --------------------------------------------------------
    def submit(self, prompt=None, max_new_tokens=None, session=None,
               sampling=None, features=None, version_pin=None):
        """Accept one request (returns its FleetRequest handle), or
        fast-fail with the typed ``Overloaded`` error once the global
        queue bound is hit — shed requests are counted against the SLO
        error budget and never journaled. ``sampling``: per-request
        ``SamplingParams`` (or its dict form); journaled with the
        request, so an at-least-once re-dispatch to a survivor replica
        re-executes with the SAME params + seed — deterministic
        counter-keyed sampling keeps the exactly-once dedup valid for
        stochastic traffic too.

        ``features`` (serving.sparse): a SCORING payload instead of a
        decode one — dict of field -> ragged id list for a replica
        fronting a ``ScoringEngine``. Journaled + resubmitted exactly
        like decode work (scoring is deterministic at a pinned cache
        version, so re-execution composes with the dedup); the score
        arrives on the handle's ``score`` with empty ``tokens``, the
        served cache version on ``versions``."""
        if features is not None:
            prompt, max_new = [], 0
            if isinstance(features, dict):
                # normalize to wire-safe plain types ONCE at the front
                # door (numpy ints/arrays in id lists would otherwise
                # die inside dispatch's json.dumps with an opaque
                # terminal error a direct ScoringEngine accepts fine)
                features = json.loads(json.dumps(
                    features,
                    default=lambda o: o.tolist()
                    if hasattr(o, "tolist") else repr(o)))
        else:
            prompt = [int(t) for t in prompt]
            max_new = int(max_new_tokens)
        if sampling is not None and not isinstance(sampling, dict):
            sampling = sampling.to_dict()      # SamplingParams → wire
        with self._cv:
            if self._closed:
                raise RuntimeError("router is closed")
            queued = len(self._queue)
            if queued >= self._max_queue:
                self.stats["shed"] += 1
                FLEET_SHED.inc(router=self.name)
                err = Overloaded(queued, self._max_queue)
                # the SLO error budget counts shed requests: a
                # serving_request row with the error lands under the
                # router's label (no engine ever saw the request, so
                # this cannot double-count)
                _monrt.on_serving_request(
                    engine=self.name, tokens=0,
                    prompt_len=len(prompt), error=repr(err))
                raise err
            self._submits_since_sweep += 1
            if self._submits_since_sweep >= _JOURNAL_SWEEP_EVERY:
                self._submits_since_sweep = 0
                self._sweep_journal_locked()
            rid = "%s-%06d" % (self._id, next(self._seq))
            handle = FleetRequest(rid, prompt, max_new, session=session,
                                  sampling=sampling, features=features)
            self._journal[rid] = {
                "rid": rid, "prompt": prompt, "max_new": max_new,
                "session": session, "sampling": sampling,
                "features": features, "version_pin": version_pin,
                "state": _QUEUED, "replica": None,
                "attempts": 0, "handle": handle,
            }
            mir = self._mirror
            if mir is not None and features is None \
                    and self._sampled(rid, mir["fraction"]):
                if mir["mode"] == "shadow":
                    # duplicate to a candidate, off the serving path:
                    # the copy is scored against the incumbent's
                    # result and joined by rid, never delivered
                    self._mirror_jobs[rid] = {
                        "t0": time.monotonic(), "inc": None,
                        "cand": None, "version": mir["version"]}
                    self._mirror_queue.append(rid)
                    self.stats["mirrored"] += 1
                    FLEET_MIRRORED.inc(router=self.name)
                else:
                    # canary split: dispatch prefers candidate slots
                    # for this rid (incumbent fallback — the split
                    # must never strand or shed work)
                    self._journal[rid]["canary"] = True
                    self.stats["canary"] += 1
                    FLEET_CANARY.inc(router=self.name)
            self._queue.append(rid)
            self.stats["requests"] += 1
            FLEET_REQUESTS.inc(router=self.name)
            FLEET_QUEUE_DEPTH.set(len(self._queue), router=self.name)
            self._cv.notify_all()
        return handle

    def generate_many(self, prompts, max_new_tokens, session=None,
                      sampling=None, timeout=300.0):
        """Synchronous convenience mirroring Engine.generate_many:
        submit every prompt, block for all results in input order.
        ``sampling`` applies to every prompt (one params dict)."""
        n = len(prompts)
        if not hasattr(max_new_tokens, "__len__"):
            max_new_tokens = [max_new_tokens] * n
        handles = [self.submit(p, m, session=session,
                               sampling=sampling)
                   for p, m in zip(prompts, max_new_tokens)]
        return [h.result(timeout=timeout) for h in handles]

    def replicas(self):
        """Live replica map {slot: endpoint} as the router sees it
        (draining slots included — they still serve POLL/CANC)."""
        with self._lock:
            return {s: r["endpoint"] for s, r in self._replicas.items()}

    def draining(self):
        """Slots currently closed to new dispatch (drain mark seen in
        the registry, or a DRNG NACK received ahead of it)."""
        with self._lock:
            return set(self._draining)

    # -- canary analysis plane (ISSUE 19) ----------------------------------
    @staticmethod
    def _sampled(rid, fraction):
        """Deterministic per-request sampling decision, keyed on the
        durable rid — a resubmitted id samples identically, and a
        replayed log reproduces the same mirror population."""
        if fraction >= 1.0:
            return True
        if fraction <= 0.0:
            return False
        return (zlib.crc32(rid.encode()) & 0xffff) / 65536.0 < fraction

    def arm_shadow(self, version, fraction=None):
        """Arm SHADOW mirroring: a deterministic ``fraction`` sample of
        accepted decode requests is duplicated to candidate replicas
        (role ``candidate``) — scored against the incumbent's served
        result, joined by rid into ``mirror_pair`` rows, never served,
        never counted in the incumbent's SLO histograms."""
        frac = float(fraction if fraction is not None
                     else _flag("serving_mirror_fraction", 0.25))
        with self._cv:
            self._mirror = {"mode": "shadow", "version": str(version),
                            "fraction": frac}
            self._cv.notify_all()

    def arm_canary(self, version, weight=None):
        """Arm the CANARY split: the sampled ``weight`` fraction of
        accepted requests is served FOR REAL by candidate replicas
        (version stamped on row/span/lease); everything else stays on
        incumbents. Candidates at their window — or dead — fall back
        to incumbents: the split can shift load but never shed."""
        frac = float(weight if weight is not None
                     else _flag("serving_canary_weight", 0.1))
        with self._cv:
            self._mirror = {"mode": "canary", "version": str(version),
                            "fraction": frac}
            self._cv.notify_all()

    def disarm_mirror(self):
        """Return to single-version routing: stop sampling, abandon
        pending shadow work (best-effort by contract), and evict
        candidate slots from dispatch — their unfinished CANARY
        requests resubmit to incumbents, so exactly-once completion
        holds through a rollback."""
        with self._cv:
            self._mirror = None
            self._mirror_queue.clear()
            dropped = len(self._mirror_jobs)
            self._mirror_jobs.clear()
            if dropped:
                self.stats["mirror_dropped"] += dropped
                FLEET_MIRROR_DROPPED.inc(dropped, router=self.name)
            cands = [(s, self._replicas[s]["endpoint"])
                     for s in self._replicas if s >= _CAND_BASE]
            self._cv.notify_all()
        for slot, ep in cands:
            self._replica_down(slot, ep, "mirror_disarmed")

    def mirror_status(self):
        """Live mirror snapshot: mode/version/fraction, resolved
        candidate slots (un-offset), and the pair/drop ledger."""
        with self._lock:
            return {
                "mirror": dict(self._mirror) if self._mirror else None,
                "candidates": {
                    s - _CAND_BASE: self._replicas[s]["endpoint"]
                    for s in self._replicas if s >= _CAND_BASE},
                "versions": {s - _CAND_BASE: v for s, v
                             in self._cand_versions.items()},
                "pending": len(self._mirror_queue)
                + sum(1 for j in self._mirror_jobs.values()
                      if j["cand"] is None),
                "pairs": self.stats["mirror_pairs"],
                "dropped": self.stats["mirror_dropped"],
            }

    def wait_for_candidates(self, n, timeout=30.0):
        """Block until >= n candidate replicas are resolved and
        dispatchable (mirror must be armed — resolution is gated on
        it)."""
        deadline = time.time() + timeout
        while True:
            with self._lock:
                have = sum(1 for s in self._replicas
                           if s >= _CAND_BASE
                           and s not in self._draining)
            if have >= n:
                return have
            if time.time() >= deadline:
                raise TimeoutError(
                    "router resolved %d of %d candidates" % (have, n))
            time.sleep(0.02)

    def wait_for_replicas(self, n, timeout=30.0):
        """Block until the router has resolved >= n live replicas."""
        deadline = time.time() + timeout
        while True:
            reps = self.replicas()
            if len(reps) >= n:
                return reps
            if time.time() >= deadline:
                raise TimeoutError(
                    "router resolved %d of %d replicas"
                    % (len(reps), n))
            time.sleep(0.02)

    def close(self):
        """Stop the router. Journaled requests not yet completed fail
        (their ``result()`` raises)."""
        with self._cv:
            if self._closed:
                return
            self._closed = True
            self._stop.set()
            self._cv.notify_all()
        for t in self._threads:
            t.join(timeout=5)
        for t in list(self._pollers.values()):
            t.join(timeout=5)
        with self._lock:
            pending = [e for e in self._journal.values()
                       if e["state"] in (_QUEUED, _INFLIGHT)]
            replicas = list(self._replicas.values())
            self._replicas = {}
            self._queue.clear()
            FLEET_QUEUE_DEPTH.set(0, router=self.name)
        for e in pending:
            self._fail_entry(e, RuntimeError("router closed"))
        for r in replicas:
            r["client"].close()
        self._kv.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- journal state transitions (always under self._lock) ---------------
    def _sweep_journal_locked(self):
        """Amortized retention sweep (every _JOURNAL_SWEEP_EVERY
        submits): drop terminal entries past the late-duplicate dedup
        window — a pruned id's eventual late result is acked as
        unknown. The caller-owned FleetRequest handle is unaffected."""
        cutoff = time.perf_counter() - _JOURNAL_KEEP_S
        dead = [rid for rid, e in self._journal.items()
                if e["state"] in (_DONE, _FAILED)
                and e["handle"].t_done is not None
                and e["handle"].t_done < cutoff]
        for rid in dead:
            del self._journal[rid]

    def _fail_entry(self, entry, err):
        entry["state"] = _FAILED
        self.stats["failed"] += 1
        if self._mirror_jobs.pop(entry["rid"], None) is not None:
            # no served result, no pair: the mirror copy is abandoned
            # (best-effort by contract)
            self.stats["mirror_dropped"] += 1
            FLEET_MIRROR_DROPPED.inc(router=self.name)
        h = entry["handle"]
        if h.t_done is None:
            h.t_done = time.perf_counter()
        h._error = err
        h._event.set()

    def _complete(self, slot, res):
        """One delivered result (poller thread). Returns True when the
        result should be ACKED to the delivering replica (always —
        even a duplicate: the replica may forget it either way)."""
        rid = res.get("id")
        with self._cv:
            if slot >= _CAND_BASE and rid in self._mirror_jobs:
                # SHADOW copy's result from a candidate: never
                # delivered — stash it and try the join. (A canary
                # result from a candidate slot is NOT in _mirror_jobs
                # and falls through to the normal path below.)
                self._mirror_inflight.get(slot, set()).discard(rid)
                self._mirror_jobs[rid]["cand"] = res
                self._try_join_locked(rid)
                return True
            entry = self._journal.get(rid)
            if entry is None:
                return True              # unknown id (pruned/foreign)
            if slot >= _CAND_BASE and not entry.get("canary"):
                # LATE SHADOW result whose mirror job was already
                # dropped (disarm, sweep timeout, or candidate
                # eviction — the poller drains for a grace window
                # past all three): ack and drop. Only canary-marked
                # entries may ever be completed by a candidate slot;
                # anything else would serve candidate-generated
                # tokens from an unvetted artifact — a rolled-back
                # rollout must have served ZERO candidate-only tokens.
                self.stats["mirror_dropped"] += 1
                FLEET_MIRROR_DROPPED.inc(router=self.name)
                return True
            if "error" in res:
                # replica-side failure (its engine died mid-request):
                # at-least-once dispatch handles it — requeue for a
                # survivor, but ONLY when the error comes from the
                # replica the entry is currently in flight on. A late
                # error from an evicted replica whose work was already
                # resubmitted must not yank the survivor's copy back
                # onto the queue (double decode / spurious attempts).
                if entry["state"] == _INFLIGHT \
                        and entry["replica"] == slot:
                    self._requeue_locked(entry, "replica error %s"
                                         % res["error"])
                return True
            if entry["state"] in (_DONE, _FAILED):
                # the exactly-once heart: a slow-but-alive replica's
                # late result for an id a survivor already completed
                # is DEDUPED here, never delivered twice — and a late
                # success for a TERMINALLY FAILED entry must not
                # resurrect it (its result() already raised)
                self.stats["duplicates"] += 1
                FLEET_DUPLICATES.inc(router=self.name)
                return True
            cur = entry["replica"]
            if cur is not None:
                self._inflight.get(cur, set()).discard(rid)
            entry["state"] = _DONE
            self.stats["completed"] += 1
            if cur is not None and cur >= _CAND_BASE:
                # canary-served for real by a candidate (the forced-
                # FAIL gate asserts this stays 0 when a rollout never
                # reaches the canary phase)
                self.stats["canary_served"] += 1
            h = entry["handle"]
            h.tokens = list(res["tokens"])
            h.score = res["score"]
            if res.get("versions") is not None:
                h.versions = res["versions"]   # scoring cache version
            h.resubmits = max(0, entry["attempts"] - 1)
            h.t_done = time.perf_counter()
            h._event.set()
            job = self._mirror_jobs.get(rid)
            if job is not None:
                # incumbent side of a shadow pair: stash the SERVED
                # tokens for the join (order-independent with the
                # candidate's result)
                job["inc"] = {"tokens": list(h.tokens)}
                self._try_join_locked(rid)
            self._cv.notify_all()        # capacity freed
        return True

    def _try_join_locked(self, rid):
        """Join one shadow pair (under the lock): once BOTH the
        incumbent's served tokens and the candidate's scored result
        are stashed, score agreement (exact token equality) and match
        (common-prefix fraction) and emit the ``mirror_pair`` row the
        token-agreement delta objective samples. A candidate-side
        error joins as a disagreeing pair carrying the error — the
        error-rate delta's evidence."""
        job = self._mirror_jobs.get(rid)
        if job is None or job["inc"] is None or job["cand"] is None:
            return
        del self._mirror_jobs[rid]
        inc_toks = job["inc"]["tokens"]
        cand = job["cand"]
        cerr = cand.get("error")
        if cerr is None:
            ctoks = list(cand.get("tokens") or ())
            agree = ctoks == inc_toks
            k = 0
            for a, b in zip(ctoks, inc_toks):
                if a != b:
                    break
                k += 1
            match = k / max(len(ctoks), len(inc_toks), 1)
        else:
            agree, match = False, 0.0
        self.stats["mirror_pairs"] += 1
        _monrt.on_mirror_pair(job["version"], rid, agree, match,
                              router=self.name, candidate_error=cerr)

    def _requeue_locked(self, entry, why):
        """Under the lock: put an unfinished entry back on the dispatch
        queue (resubmission) — or fail it when its attempt budget is
        spent (a request that somehow kills every replica it touches
        must not ping-pong forever)."""
        rid = entry["rid"]
        cur = entry["replica"]
        if cur is not None:
            self._inflight.get(cur, set()).discard(rid)
        entry["replica"] = None
        if entry["attempts"] >= self._max_attempts:
            self._fail_entry(entry, RuntimeError(
                "request %s exhausted %d attempts (last: %s)"
                % (rid, entry["attempts"], why)))
            return
        entry["state"] = _QUEUED
        self._queue.appendleft(rid)
        self.stats["resubmissions"] += 1
        FLEET_RESUBMISSIONS.inc(router=self.name)
        FLEET_QUEUE_DEPTH.set(len(self._queue), router=self.name)
        self._cv.notify_all()

    # -- replica lifecycle -------------------------------------------------
    def _add_replica(self, slot, endpoint):
        with self._lock:
            if self._closed or slot in self._replicas:
                return
            self._replicas[slot] = {
                "endpoint": endpoint,
                "client": ReplicaClient(endpoint,
                                        timeout=self._client_timeout,
                                        retry=self._retry),
            }
            self._inflight.setdefault(slot, set())
            # a fresh incarnation starts dispatchable — the drain mark
            # belonged to the slot's PREVIOUS holder
            self._draining.discard(slot)
            self._cv.notify_all()
        t = threading.Thread(
            target=self._poller_loop, args=(slot, endpoint),
            daemon=True, name="ptpu-%s-poll-%d" % (self.name, slot))
        self._pollers[slot] = t
        t.start()

    def _replica_down(self, slot, endpoint, reason):
        """Evict a replica from dispatch and RESUBMIT its unfinished
        journal entries to the survivors. Idempotent per (slot,
        endpoint) incarnation. For a stall (live-but-wedged holder) the
        registry slot is tombstoned so the supervisor respawns it and
        the wedged holder's expect-guarded lease keepalive loses."""
        with self._cv:
            info = self._replicas.get(slot)
            if info is None or info["endpoint"] != endpoint:
                return False             # already handled / replaced
            del self._replicas[slot]
            self._draining.discard(slot)
            self._cand_versions.pop(slot, None)
            rids = self._inflight.pop(slot, set())
            for rid in list(rids):
                entry = self._journal.get(rid)
                if entry is not None and entry["state"] == _INFLIGHT:
                    self._requeue_locked(entry, "replica %d %s"
                                         % (slot, reason))
            # shadow copies on a dead candidate are DROPPED, never
            # requeued — the mirror is best-effort and must not feed
            # work back into the serving path
            for rid in self._mirror_inflight.pop(slot, ()):
                if self._mirror_jobs.pop(rid, None) is not None:
                    self.stats["mirror_dropped"] += 1
                    FLEET_MIRROR_DROPPED.inc(router=self.name)
            for sess in [s for s, r in self._affinity.items()
                         if r == slot]:
                del self._affinity[sess]
            self.stats["evictions"][reason] = \
                self.stats["evictions"].get(reason, 0) + 1
            FLEET_EVICTIONS.inc(reason=reason)
        info["client"].close()
        if reason == "mirror_disarmed":
            # disarm eviction is ROUTER-LOCAL bookkeeping: the
            # candidate cell is healthy and its lease must survive —
            # the shadow->canary flip (and a later rollout) re-resolves
            # the same holders when the mirror re-arms. Tombstoning
            # here would make the live holder's keepalive lose and
            # turn every flip into a reap-and-respawn cycle.
            return True
        if slot >= _CAND_BASE:
            key = (_membership.role_prefix(CANDIDATE_ROLE)
                   + str(slot - _CAND_BASE))
        else:
            key = _membership.role_prefix(self.role) + str(slot)
        try:
            # tombstone (never delete): see EVICTED_PREFIX. The live
            # value may carry marks — candidates boot as
            # ``version:<ver>:<ep>``, drains re-mark ``draining:<ep>``
            # — so CAS against what the registry ACTUALLY holds; a
            # bare-endpoint expect would never match a marked lease,
            # the wedged holder's expect-guarded keepalive would keep
            # winning, and stall recovery would degrade into evict /
            # re-add churn instead of a supervisor respawn. A dead
            # holder's key may already be gone (get -> None) or the
            # value may have moved between get and CAS — the CAS just
            # fails; tombstoning is best-effort either way.
            cur = self._kv.get(key)
            if cur is not None \
                    and not cur.startswith(EVICTED_PREFIX) \
                    and _strip_marks(cur)[1] == endpoint:
                self._kv.cas(key, cur, EVICTED_PREFIX + endpoint,
                             ttl=max(10.0, 4 * self._stall_timeout))
        except RETRYABLE:
            pass
        return True

    # -- loops -------------------------------------------------------------
    def _registry_loop(self):
        while not self._stop.wait(self._refresh):
            try:
                raw = _membership.live_endpoints(self._kv, self.role)
            except RETRYABLE:
                continue
            live, marked, versions = {}, set(), {}
            for slot, ep in raw.items():
                if ep.startswith(EVICTED_PREFIX):
                    continue
                if ep.startswith(DRAINING_PREFIX):
                    # drain-marked lease: STILL LIVE (the poller keeps
                    # draining in-flight results) but closed to new
                    # dispatch; strip the mark to recover the endpoint
                    ep = ep[len(DRAINING_PREFIX):]
                    marked.add(slot)
                live[slot] = ep
            if self._mirror is not None:
                # mirror armed: additionally resolve CANDIDATE leases
                # under offset keys — same eviction / drain / poller
                # machinery, separate role registry. Disarmed, the
                # role is never read and any lingering candidate slots
                # fall out of `live` -> evicted below.
                try:
                    rawc = _membership.live_endpoints(self._kv,
                                                     CANDIDATE_ROLE)
                except RETRYABLE:
                    rawc = {}
                for slot, val in rawc.items():
                    if val.startswith(EVICTED_PREFIX):
                        continue
                    drain = val.startswith(DRAINING_PREFIX)
                    ver, ep = _strip_marks(val)
                    slot += _CAND_BASE
                    if drain:
                        marked.add(slot)
                    if ver is not None:
                        versions[slot] = ver
                    live[slot] = ep
            with self._lock:
                known = {s: r["endpoint"]
                         for s, r in self._replicas.items()}
                # a drain mark is terminal for the incarnation: union
                # new marks (a DRNG NACK may have added one ahead of
                # the registry), drop slots that left the registry
                self._draining |= marked
                self._draining &= set(live)
                self._cand_versions.update(versions)
            for slot, ep in known.items():
                if live.get(slot) != ep:
                    # lease expired (dead) or a replacement claimed the
                    # slot at a new endpoint
                    self._replica_down(slot, ep, "lease_expired")
            for slot, ep in live.items():
                if known.get(slot) != ep:
                    self._add_replica(slot, ep)
            with self._lock:
                # incumbents only: candidate capacity must not inflate
                # the fleet-size gauge the autoscaler converges on
                FLEET_REPLICAS.set(
                    sum(1 for s in self._replicas if s < _CAND_BASE),
                    router=self.name)

    def _dispatch_loop(self):
        while True:
            with self._cv:
                rid = slot = None
                while not self._stop.is_set():
                    # drop stale heads: an entry a slow replica's late
                    # result completed WHILE it sat requeued must not
                    # be re-executed (its state already left _QUEUED)
                    dropped = False
                    while self._queue and self._journal[
                            self._queue[0]]["state"] != _QUEUED:
                        self._queue.popleft()
                        dropped = True
                    if dropped:
                        # every queue mutation updates the gauge — a
                        # queue drained entirely by stale-head drops
                        # must not leave a phantom depth pinning the
                        # signals queue alert (and blocking its
                        # scale-down hint) forever
                        FLEET_QUEUE_DEPTH.set(len(self._queue),
                                              router=self.name)
                    if self._queue:
                        # draining slots are NOT dispatch candidates
                        # (they'd NACK); they still serve POLL/CANC
                        loads = {s: len(self._inflight.get(s, ()))
                                 for s in self._replicas
                                 if s not in self._draining}
                        entry = self._journal[self._queue[0]]
                        if entry.get("canary") \
                                and entry["attempts"] == 0:
                            # canary-sampled, first attempt: prefer
                            # candidate slots; fall back to incumbents
                            # when none are live/under-window (the
                            # split must never shed or strand work).
                            # A RESUBMISSION may land anywhere —
                            # exactly-once completion outranks the
                            # split after a candidate death.
                            cand = {s: l for s, l in loads.items()
                                    if s >= _CAND_BASE}
                            if cand and any(l < self._window
                                            for l in cand.values()):
                                loads = cand
                        elif not entry.get("canary"):
                            # candidates never serve unsampled traffic
                            loads = {s: l for s, l in loads.items()
                                     if s < _CAND_BASE}
                        slot = choose_replica(
                            loads, self._window,
                            session=entry["session"],
                            affinity=self._affinity)
                        if slot is not None:
                            rid = self._queue.popleft()
                            FLEET_QUEUE_DEPTH.set(
                                len(self._queue), router=self.name)
                            break
                    self._cv.wait(timeout=0.25)
                if rid is None:
                    return               # stopping
                entry = self._journal[rid]
                entry["state"] = _INFLIGHT
                entry["replica"] = slot
                entry["attempts"] += 1
                self._inflight[slot].add(rid)
                if entry["session"] is not None:
                    self._affinity[entry["session"]] = slot
                    self._affinity.move_to_end(entry["session"])
                    while len(self._affinity) > _AFFINITY_MAX:
                        self._affinity.popitem(last=False)
                info = self._replicas[slot]
            # wire work OUTSIDE the lock; the dispatch span carries
            # rid/slot/endpoint — a resubmitted id shows N dispatch
            # spans with different endpoints (the resubmission hop)
            attrs = {}
            if slot >= _CAND_BASE:
                # canary dispatch: the candidate's artifact version on
                # the span (the row carries it via the engine)
                attrs["version"] = self._cand_versions.get(slot)
            try:
                with _trace.span("router.dispatch", rid=rid, slot=slot,
                                 endpoint=info["endpoint"],
                                 attempt=entry["attempts"], **attrs):
                    info["client"].submit(
                        rid, entry["prompt"], entry["max_new"],
                        entry.get("sampling"),
                        features=entry.get("features"),
                        version_pin=entry.get("version_pin"))
            except RETRYABLE:
                self._replica_down(slot, info["endpoint"], "dispatch")
            except ReplicaDraining:
                # typed drain NACK: the replica is healthy but closed
                # to admissions (we raced its drain mark). Requeue
                # WITHOUT burning the attempt budget — admission was
                # refused, not tried — and stop dispatching to the
                # slot even before the lease mark propagates.
                with self._cv:
                    self._draining.add(slot)
                    e2 = self._journal.get(rid)
                    if e2 is not None and e2["state"] == _INFLIGHT \
                            and e2["replica"] == slot:
                        self._inflight.get(slot, set()).discard(rid)
                        e2["replica"] = None
                        e2["attempts"] -= 1
                        e2["state"] = _QUEUED
                        self._queue.appendleft(rid)
                        self.stats["drain_nacks"] += 1
                        FLEET_QUEUE_DEPTH.set(len(self._queue),
                                              router=self.name)
                        self._cv.notify_all()
            except Exception as e:
                # typed rejection (BADR) or another terminal error:
                # fail THIS request, not the replica
                with self._cv:
                    e2 = self._journal.get(rid)
                    if e2 is not None and e2["state"] == _INFLIGHT:
                        self._inflight.get(slot, set()).discard(rid)
                        self._fail_entry(e2, e)

    def _sweep_mirror_locked(self, now):
        """Drop shadow jobs past the mirror timeout (candidate never
        answered / incumbent result pruned): bounded state, and a
        wedged candidate cannot pin join stashes forever."""
        stale = [rid for rid, j in self._mirror_jobs.items()
                 if now - j["t0"] > self._mirror_timeout]
        for rid in stale:
            del self._mirror_jobs[rid]
            self.stats["mirror_dropped"] += 1
            FLEET_MIRROR_DROPPED.inc(router=self.name)
            for s in self._mirror_inflight.values():
                s.discard(rid)

    def _mirror_loop(self):
        """Dispatch SHADOW duplicates to candidate replicas — its own
        thread with its own clients (ReplicaClient sockets are never
        shared across threads). Best-effort by contract: a failed or
        timed-out duplicate is dropped and counted, never requeued
        into the serving path, and never touches the journal's state
        machine — a broken candidate can cost pairs, not traffic."""
        clients = {}             # offset slot -> (endpoint, client)
        try:
            while True:
                with self._cv:
                    rid = slot = None
                    while not self._stop.is_set():
                        self._sweep_mirror_locked(time.monotonic())
                        if self._mirror_queue:
                            if self._mirror_queue[0] \
                                    not in self._mirror_jobs:
                                # dropped (disarm/timeout/fail): skip
                                self._mirror_queue.popleft()
                                continue
                            loads = {s: len(self._mirror_inflight
                                            .get(s, ()))
                                     for s in self._replicas
                                     if s >= _CAND_BASE
                                     and s not in self._draining}
                            slot = choose_replica(loads, self._window)
                            if slot is not None:
                                rid = self._mirror_queue.popleft()
                                break
                        self._cv.wait(timeout=0.25)
                    if rid is None:
                        return           # stopping
                    entry = self._journal.get(rid)
                    if entry is None:
                        if self._mirror_jobs.pop(rid, None) \
                                is not None:
                            self.stats["mirror_dropped"] += 1
                            FLEET_MIRROR_DROPPED.inc(router=self.name)
                        continue
                    self._mirror_inflight.setdefault(slot,
                                                     set()).add(rid)
                    ep = self._replicas[slot]["endpoint"]
                    ver = self._cand_versions.get(slot)
                    prompt = entry["prompt"]
                    max_new = entry["max_new"]
                    sampling = entry.get("sampling")
                tup = clients.get(slot)
                if tup is None or tup[0] != ep:
                    if tup is not None:
                        tup[1].close()
                    tup = (ep, ReplicaClient(
                        ep, timeout=self._client_timeout,
                        retry=self._retry))
                    clients[slot] = tup
                try:
                    with _trace.span("router.mirror", rid=rid,
                                     slot=slot, endpoint=ep,
                                     version=ver):
                        tup[1].submit(rid, prompt, max_new, sampling)
                except Exception:
                    # candidate unreachable / NACK / reject: drop the
                    # copy (the served request is untouched)
                    tup[1].close()
                    with self._cv:
                        self._mirror_inflight.get(slot,
                                                  set()).discard(rid)
                        if self._mirror_jobs.pop(rid, None) \
                                is not None:
                            self.stats["mirror_dropped"] += 1
                            FLEET_MIRROR_DROPPED.inc(router=self.name)
        finally:
            for _, cl in clients.values():
                cl.close()

    def _poller_loop(self, slot, endpoint):
        """Long-poll one replica for finished results and ack them.
        A poll that fails past the retry deadline reports the replica
        down (stall watchdog). After a STALL eviction the poller keeps
        DRAINING for a grace window: the wedged replica's engine kept
        decoding, and its late results must reach the journal's dedup
        (and be acked) rather than be abandoned mid-socket."""
        client = ReplicaClient(endpoint, timeout=self._client_timeout,
                               retry=self._retry)
        draining_until = None
        try:
            while not self._stop.is_set():
                with self._lock:
                    info = self._replicas.get(slot)
                    live = (info is not None
                            and info["endpoint"] == endpoint)
                if not live and draining_until is None:
                    draining_until = time.monotonic() + max(
                        10.0, 3 * self._stall_timeout)
                if draining_until is not None \
                        and time.monotonic() > draining_until:
                    return
                try:
                    done = client.poll(wait=self._poll_wait)
                except RETRYABLE:
                    if live:
                        # nothing answered for the whole retry
                        # deadline. Registry still listing the
                        # endpoint = live-but-wedged holder (stall
                        # watchdog eviction); gone = plain death.
                        reason = "stall"
                        try:
                            if slot >= _CAND_BASE:
                                val = _membership.live_endpoints(
                                    self._kv, CANDIDATE_ROLE
                                    ).get(slot - _CAND_BASE)
                                if val is None or \
                                        _strip_marks(val)[1] \
                                        != endpoint:
                                    reason = "dead"
                            elif _membership.live_endpoints(
                                    self._kv, self.role
                                    ).get(slot) != endpoint:
                                reason = "dead"
                        except RETRYABLE:
                            pass
                        self._replica_down(slot, endpoint, reason)
                        draining_until = time.monotonic() + max(
                            10.0, 3 * self._stall_timeout)
                        if reason == "dead":
                            return
                        continue         # drain: the wedge may lift
                    # draining through a still-wedged endpoint: keep
                    # trying until the grace window closes — the late
                    # results behind the wedge are the whole point
                    continue
                if done:
                    for res in done:
                        self._complete(slot, res)
                    try:
                        # one batched ack per delivery round trip
                        client.cancel([res["id"] for res in done])
                    except RETRYABLE:
                        pass             # re-delivered next poll; dedup
        finally:
            client.close()


# -- supervisor -------------------------------------------------------------

class Supervisor:
    """Keeps ``desired`` replicas registered: watches the role registry
    and respawns a cell (via the factory callback) for every slot whose
    lease expired or that the router tombstoned. The factory returns a
    ``Replica`` (it claims the freed slot itself through
    register_endpoint); ``cells`` keeps every incarnation for test
    teardown, ``respawns`` counts replacements."""

    def __init__(self, kv, spawn_fn, desired, role=REPLICA_ROLE,
                 interval=0.1):
        self._kv = kv
        self._spawn = spawn_fn
        self.desired = int(desired)
        self.role = role
        self._interval = float(interval)
        self._stop = threading.Event()
        self.cells = []
        self.respawns = 0
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="ptpu-fleet-supervisor")

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=10)

    def _loop(self):
        prefix = _membership.role_prefix(self.role)
        while not self._stop.wait(self._interval):
            try:
                live = _membership.live_endpoints(self._kv, self.role)
            except RETRYABLE:
                continue
            # free tombstoned slots (compare-and-delete: never remove a
            # slot a fresh holder already re-claimed)
            alive = 0
            for slot, val in live.items():
                if val.startswith(EVICTED_PREFIX):
                    try:
                        self._kv.cad(prefix + str(slot), val)
                    except RETRYABLE:
                        pass
                else:
                    alive += 1
            for _ in range(self.desired - alive):
                if self._stop.is_set():
                    return
                try:
                    cell = self._spawn()
                except Exception:
                    break                # factory failed; retry next tick
                self.cells.append(cell)
                self.respawns += 1
