// C inference API — the non-Python deployment entry point.
//
// Reference capability: paddle/capi/gradient_machine.h:36-62
// (paddle_gradient_machine_create_for_inference / _forward): a C program
// loads a trained model and runs forward passes. Here the engine is
// XLA-through-JAX, so the C ABI embeds a CPython interpreter and drives
// the same `fluid.io.load_inference_model` + Executor path a Python
// deployment would use — one process, one interpreter, no IPC. The C
// surface stays engine-agnostic: floats in, floats out.
//
//   void* pt_predictor_create(const char* model_dir);
//   int   pt_predictor_run(void* p,
//                          const float* in, const int64_t* shape, int nd,
//                          float* out, int64_t out_cap,
//                          int64_t* out_shape, int* out_nd);
//       out_shape must have at least 8 slots (max supported rank);
//       higher-rank fetches fail with an error instead of truncating.
//   void  pt_predictor_destroy(void* p);
//   const char* pt_last_error();
//
// Two run surfaces: pt_predictor_run (single float feed/fetch — the
// common serving shape) and pt_predictor_run_multi (multiple NAMED typed
// feeds and every model fetch, dtype codes 0=f32 1=i32 2=i64 — the
// reference's Arguments-based C API, gradient_machine.h:36-62, which
// carried typed matrices and ivectors for seq2seq-style models).
// Thread-safety: calls serialize on the GIL.

#include <Python.h>

#include <cstdint>
#include <cstring>
#include <string>

namespace {

std::string g_error;

void set_error_from_python() {
  PyObject *type, *value, *tb;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  PyObject* s = value ? PyObject_Str(value) : nullptr;
  const char* msg = s ? PyUnicode_AsUTF8(s) : nullptr;
  g_error = msg ? msg : "unknown python error";
  PyErr_Clear();  // AsUTF8 may raise; never leave an exception pending
  Py_XDECREF(s);
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
}

const char kHelper[] = R"PY(
import os
import numpy as np
import paddle_tpu as fluid

class _CPredictor:
    """Holds a loaded inference program + scope; run() takes/returns
    float32 numpy arrays (fluid.io.load_inference_model serving path)."""

    def __init__(self, model_dir):
        self.scope = fluid.Scope()
        self.exe = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(self.scope):
            prog, feeds, fetches = fluid.io.load_inference_model(
                model_dir, self.exe)
        self.prog, self.feeds, self.fetches = prog, feeds, fetches

    def run(self, buf, shape):
        # zero-copy in: `buf` is a C memoryview over the caller's floats
        x = np.frombuffer(buf, np.float32).reshape(shape).copy()
        with fluid.scope_guard(self.scope):
            out, = self.exe.run(self.prog, feed={self.feeds[0]: x},
                                fetch_list=self.fetches)
        out = np.ascontiguousarray(np.asarray(out), np.float32)
        return out.tobytes(), list(out.shape)

    # dtype codes of the C ABI (gradient_machine.h Arguments carried
    # typed matrices/ivectors; here: 0=float32, 1=int32, 2=int64)
    _DT = {0: np.float32, 1: np.int32, 2: np.int64}
    _DT_CODE = {np.dtype(np.float32): 0, np.dtype(np.int32): 1,
                np.dtype(np.int64): 2}

    def run_multi(self, names, bufs, shapes, dtypes):
        """Multiple named typed feeds -> every fetch of the model, in
        model order, each as (bytes, shape, dtype_code)."""
        feed = {}
        for nm, b, shp, dt in zip(names, bufs, shapes, dtypes):
            feed[nm] = np.frombuffer(
                b, self._DT[int(dt)]).reshape(shp).copy()
        missing = [n for n in self.feeds if n not in feed]
        if missing:
            raise ValueError("missing feeds %s (model wants %s)"
                             % (missing, self.feeds))
        with fluid.scope_guard(self.scope):
            outs = self.exe.run(self.prog, feed=feed,
                                fetch_list=self.fetches)
        res = []
        for o in outs:
            a = np.ascontiguousarray(np.asarray(o))
            code = self._DT_CODE.get(a.dtype)
            if code is None:
                a = np.ascontiguousarray(a, np.float32)
                code = 0
            res.append((a.tobytes(), list(a.shape), code))
        return res

    def num_fetches(self):
        return len(self.fetches)
)PY";

struct Predictor {
  PyObject* obj;  // _CPredictor instance
};

PyObject* g_namespace = nullptr;

bool g_we_initialized = false;

bool ensure_python() {
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
    g_we_initialized = true;
  }
  if (g_namespace == nullptr) {
    PyObject* main_mod = PyImport_AddModule("__main__");
    g_namespace = PyModule_GetDict(main_mod);
    Py_INCREF(g_namespace);
    PyObject* r = PyRun_String(kHelper, Py_file_input, g_namespace,
                               g_namespace);
    if (r == nullptr) {
      set_error_from_python();
      Py_CLEAR(g_namespace);
      return false;
    }
    Py_DECREF(r);
  }
  return true;
}

}  // namespace

extern "C" {

const char* pt_last_error() { return g_error.c_str(); }

void* pt_predictor_create(const char* model_dir) {
  bool had_python = Py_IsInitialized();
  PyGILState_STATE gil = PyGILState_LOCKED;
  if (had_python) gil = PyGILState_Ensure();
  void* result = nullptr;
  bool ok = ensure_python();
  if (ok) {
    PyObject* cls = PyDict_GetItemString(g_namespace, "_CPredictor");
    PyObject* obj =
        cls ? PyObject_CallFunction(cls, "s", model_dir) : nullptr;
    if (obj == nullptr) {
      set_error_from_python();
    } else {
      Predictor* p = new Predictor{obj};
      result = p;
    }
  }
  if (had_python) {
    PyGILState_Release(gil);
  } else if (ok || g_we_initialized) {
    // we created the interpreter on this thread: release the GIL so other
    // threads' PyGILState_Ensure can proceed (serving pattern: create on
    // main, run on workers)
    PyEval_SaveThread();
  }
  return result;
}

int pt_predictor_run(void* handle, const float* in, const int64_t* shape,
                     int nd, float* out, int64_t out_cap,
                     int64_t* out_shape, int* out_nd) {
  Predictor* p = static_cast<Predictor*>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  int64_t n = 1;
  for (int i = 0; i < nd; ++i) n *= shape[i];

  // buffer-protocol marshalling: no per-element boxing on the hot path
  PyObject* mv = PyMemoryView_FromMemory(
      const_cast<char*>(reinterpret_cast<const char*>(in)),
      n * int64_t(sizeof(float)), PyBUF_READ);
  PyObject* shp = PyList_New(nd);
  for (int i = 0; i < nd; ++i) {
    PyList_SET_ITEM(shp, i, PyLong_FromLongLong(shape[i]));
  }
  PyObject* res = PyObject_CallMethod(p->obj, "run", "OO", mv, shp);
  Py_DECREF(mv);
  Py_DECREF(shp);
  if (res == nullptr) {
    set_error_from_python();
  } else {
    PyObject* vals = PyTuple_GetItem(res, 0);  // bytes
    PyObject* oshp = PyTuple_GetItem(res, 1);
    char* data = nullptr;
    Py_ssize_t nbytes = 0;
    PyBytes_AsStringAndSize(vals, &data, &nbytes);
    int64_t out_n = nbytes / int64_t(sizeof(float));
    int ond = int(PyList_Size(oshp));
    if (out_n > out_cap) {
      g_error = "output buffer too small";
    } else if (ond > 8) {
      g_error = "output rank exceeds the 8-slot out_shape contract";
    } else {
      memcpy(out, data, size_t(nbytes));
      for (int i = 0; i < ond; ++i) {
        out_shape[i] = PyLong_AsLongLong(PyList_GetItem(oshp, i));
      }
      *out_nd = ond;
      rc = 0;
    }
    Py_DECREF(res);
  }
  PyGILState_Release(gil);
  return rc;
}

void pt_predictor_destroy(void* handle) {
  Predictor* p = static_cast<Predictor*>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  Py_XDECREF(p->obj);
  PyGILState_Release(gil);
  delete p;
}

// ---- multi-io surface (capi/gradient_machine.h:36-62 Arguments parity) --
// dtype codes: 0=float32, 1=int32, 2=int64. Element sizes follow.

static int64_t pt_dtype_size(int code) {
  return code == 2 ? 8 : 4;
}

int pt_predictor_num_fetches(void* handle) {
  Predictor* p = static_cast<Predictor*>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  int n = -1;
  PyObject* r = PyObject_CallMethod(p->obj, "num_fetches", nullptr);
  if (r == nullptr) {
    set_error_from_python();
  } else {
    n = int(PyLong_AsLong(r));
    Py_DECREF(r);
  }
  PyGILState_Release(gil);
  return n;
}

// Feeds: n_in named typed buffers. Fetches: the model's fetch list in
// order; out_bufs[i] has capacity out_caps_bytes[i] BYTES; shapes land in
// out_shapes[i*8 .. i*8+7] (rank out_nds[i], max rank 8); dtype code in
// out_dtypes[i]. Returns 0 on success.
int pt_predictor_run_multi(void* handle, int n_in, const char** in_names,
                           const void** in_bufs,
                           const int64_t* const* in_shapes,
                           const int* in_nds, const int* in_dtypes,
                           int n_out, void** out_bufs,
                           const int64_t* out_caps_bytes,
                           int64_t* out_shapes, int* out_nds,
                           int* out_dtypes) {
  Predictor* p = static_cast<Predictor*>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject* names = PyList_New(n_in);
  PyObject* bufs = PyList_New(n_in);
  PyObject* shapes = PyList_New(n_in);
  PyObject* dtypes = PyList_New(n_in);
  for (int i = 0; i < n_in; ++i) {
    int64_t n = 1;
    for (int d = 0; d < in_nds[i]; ++d) n *= in_shapes[i][d];
    PyList_SET_ITEM(names, i, PyUnicode_FromString(in_names[i]));
    PyList_SET_ITEM(
        bufs, i,
        PyMemoryView_FromMemory(
            const_cast<char*>(static_cast<const char*>(in_bufs[i])),
            n * pt_dtype_size(in_dtypes[i]), PyBUF_READ));
    PyObject* shp = PyList_New(in_nds[i]);
    for (int d = 0; d < in_nds[i]; ++d) {
      PyList_SET_ITEM(shp, d, PyLong_FromLongLong(in_shapes[i][d]));
    }
    PyList_SET_ITEM(shapes, i, shp);
    PyList_SET_ITEM(dtypes, i, PyLong_FromLong(in_dtypes[i]));
  }
  PyObject* res = PyObject_CallMethod(p->obj, "run_multi", "OOOO", names,
                                      bufs, shapes, dtypes);
  Py_DECREF(names);
  Py_DECREF(bufs);
  Py_DECREF(shapes);
  Py_DECREF(dtypes);
  if (res == nullptr) {
    set_error_from_python();
  } else {
    int got = int(PyList_Size(res));
    if (got != n_out) {
      g_error = "model produced " + std::to_string(got) +
                " fetches, caller expects " + std::to_string(n_out);
    } else {
      rc = 0;
      for (int i = 0; i < got && rc == 0; ++i) {
        PyObject* item = PyList_GetItem(res, i);   // (bytes, shape, code)
        PyObject* vals = PyTuple_GetItem(item, 0);
        PyObject* oshp = PyTuple_GetItem(item, 1);
        int code = int(PyLong_AsLong(PyTuple_GetItem(item, 2)));
        char* data = nullptr;
        Py_ssize_t nbytes = 0;
        PyBytes_AsStringAndSize(vals, &data, &nbytes);
        int ond = int(PyList_Size(oshp));
        if (nbytes > out_caps_bytes[i]) {
          g_error = "output " + std::to_string(i) + " needs " +
                    std::to_string(nbytes) + " bytes, buffer has " +
                    std::to_string(out_caps_bytes[i]);
          rc = -1;
        } else if (ond > 8) {
          g_error = "output rank exceeds the 8-slot out_shape contract";
          rc = -1;
        } else {
          memcpy(out_bufs[i], data, size_t(nbytes));
          for (int d = 0; d < ond; ++d) {
            out_shapes[i * 8 + d] =
                PyLong_AsLongLong(PyList_GetItem(oshp, d));
          }
          out_nds[i] = ond;
          out_dtypes[i] = code;
        }
      }
    }
    Py_DECREF(res);
  }
  PyGILState_Release(gil);
  return rc;
}

}  // extern "C"
