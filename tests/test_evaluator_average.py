"""fluid.average.WeightedAverage + fluid.evaluator façade parity
(reference python/paddle/fluid/average.py, evaluator.py)."""

import numpy as np
import pytest

import paddle_tpu as fluid


def test_weighted_average():
    wa = fluid.average.WeightedAverage()
    wa.add(2.0, 1)
    wa.add(4.0, 3)
    assert abs(wa.eval() - 3.5) < 1e-9
    wa.reset()
    with pytest.raises(ValueError):
        wa.eval()
    with pytest.raises(ValueError):
        wa.add("nope", 1)


def test_evaluator_aliases_are_metrics():
    assert fluid.evaluator.ChunkEvaluator is fluid.metrics.ChunkEvaluator
    assert fluid.evaluator.EditDistance is fluid.metrics.EditDistance


def test_detection_map_rejects_unsupported_knobs():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        det = fluid.layers.data("det", [6])
        gt = fluid.layers.data("gt", [5])
        with pytest.raises(NotImplementedError, match="difficult"):
            fluid.evaluator.DetectionMAP(det, gt, evaluate_difficult=False)
        with pytest.raises(NotImplementedError, match="11point"):
            fluid.evaluator.DetectionMAP(det, gt, ap_version="integral")


def test_detection_map_evaluator():
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        det = fluid.layers.data("det", [6])
        gt = fluid.layers.data("gt", [5])
        m = fluid.evaluator.DetectionMAP(det, gt)
        exe = fluid.Executor(fluid.CPUPlace())
        # two perfect detections -> mAP 1.0
        dv = np.array([[0, 0.9, 0, 0, 10, 10],
                       [1, 0.8, 20, 20, 30, 30]], np.float32)
        gv = np.array([[0, 0, 0, 10, 10],
                       [1, 20, 20, 30, 30]], np.float32)
        for _ in range(3):
            mv, = exe.run(main, feed={"det": dv, "gt": gv},
                          fetch_list=m.metrics)
            m.update(mv)
        out = m.eval()
    np.testing.assert_allclose(out, [1.0], rtol=1e-5)
    m.reset()
    with pytest.raises(ValueError):
        m.eval()
