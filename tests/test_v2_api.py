"""v2 façade tests (SURVEY.md M7): the reference v2 MNIST script shape —
`SGD.train(reader, event_handler)` event loop, Parameters tar round-trip,
test() without parameter updates, and paddle.v2.infer. Reference:
python/paddle/v2/trainer.py:37,137, v2/parameters.py, book
recognize_digits v2 scripts."""

import io

import numpy as np
import pytest

import paddle_tpu.v2 as paddle
import paddle_tpu as fluid


def _toy_reader(n=128, seed=0):
    rng = np.random.RandomState(seed)
    xs = rng.rand(n, 8).astype(np.float32)
    w = rng.rand(8).astype(np.float32)
    ys = (xs @ w > w.sum() / 2).astype(np.int64)

    def reader():
        for i in range(n):
            yield xs[i], int(ys[i])
    return reader


def _build_classifier():
    images = paddle.layer.data("x", paddle.data_type.dense_vector(8))
    label = paddle.layer.data("label", paddle.data_type.integer_value(2))
    hidden = paddle.layer.fc(images, 16, act="tanh")
    predict = paddle.layer.fc(hidden, 2, act="softmax")
    cost = paddle.layer.classification_cost(predict, label)
    return cost, predict


def test_v2_event_loop_trains_and_fires_events():
    paddle.init(use_gpu=False)
    cost, predict = _build_classifier()
    parameters = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=parameters,
        update_equation=paddle.optimizer.Adam(learning_rate=0.05))

    events = {"begin_pass": 0, "end_pass": 0, "iters": 0}
    costs = []

    def event_handler(event):
        if isinstance(event, paddle.event.BeginPass):
            events["begin_pass"] += 1
        elif isinstance(event, paddle.event.EndPass):
            events["end_pass"] += 1
            costs.append(event.cost)
        elif isinstance(event, paddle.event.EndIteration):
            events["iters"] += 1
            assert np.isfinite(event.cost)

    trainer.train(paddle.batch(_toy_reader(), batch_size=16),
                  num_passes=8, event_handler=event_handler)
    assert events["begin_pass"] == events["end_pass"] == 8
    assert events["iters"] == 8 * 8
    assert costs[-1] < costs[0] * 0.5, costs

    # parameters view holds real trained arrays
    assert len(parameters.keys()) >= 2
    for name in parameters:
        assert np.isfinite(parameters[name]).all()

    # test() leaves parameters untouched
    before = {n: parameters[n].copy() for n in parameters}
    result = trainer.test(paddle.batch(_toy_reader(seed=1), batch_size=16))
    assert np.isfinite(result.cost)
    for n in parameters:
        np.testing.assert_array_equal(parameters[n], before[n])

    # tar round-trip + infer parity (v2 parameters.to_tar / from_tar)
    buf = io.BytesIO()
    trainer.save_parameter_to_tar(buf)
    buf.seek(0)
    restored = paddle.parameters.Parameters.from_tar(buf)
    for n in parameters:
        np.testing.assert_array_equal(parameters[n], restored[n])

    probe = [tuple([np.random.RandomState(7).rand(8).astype(np.float32)])]
    p1 = paddle.infer(output_layer=predict, parameters=parameters,
                      input=probe)
    p2 = paddle.infer(output_layer=predict, parameters=restored,
                      input=probe)
    np.testing.assert_allclose(p1, p2, rtol=1e-6)
    assert p1.shape == (1, 2)


def test_v2_sequence_layers_compose():
    words = paddle.layer.data(
        "words", paddle.data_type.integer_value_sequence(50))
    label = paddle.layer.data("label", paddle.data_type.integer_value(2))
    emb = paddle.layer.embedding(words, size=8)
    pooled = paddle.layer.pooling(emb, pooling_type="max")
    predict = paddle.layer.fc(pooled, 2, act="softmax")
    cost = paddle.layer.classification_cost(predict, label)
    parameters = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=parameters,
        update_equation=paddle.optimizer.Adam(learning_rate=0.05))

    rng = np.random.RandomState(0)
    samples = []
    for _ in range(32):
        n = rng.randint(2, 6)
        seq = rng.randint(0, 50, n).tolist()
        samples.append((seq, int(seq[0] % 2)))

    def reader():
        yield from samples

    seen = []
    trainer.train(paddle.batch(reader, batch_size=8), num_passes=10,
                  event_handler=lambda e: seen.append(e.cost)
                  if isinstance(e, paddle.event.EndPass) else None)
    assert seen[-1] < seen[0] * 0.7, seen


def test_v2_type_errors():
    cost, _ = _build_classifier()
    parameters = paddle.parameters.create(cost)
    with pytest.raises(TypeError):
        paddle.trainer.SGD(cost, {"not": "parameters"},
                           paddle.optimizer.SGD())
    with pytest.raises(TypeError):
        paddle.trainer.SGD(cost, parameters, "not-an-optimizer")
    with pytest.raises(TypeError):
        paddle.layer.data("x", [8])   # fluid-style shape is not a v2 type


def test_v2_ploter(tmp_path):
    from paddle_tpu.v2.plot import Ploter
    p = Ploter("train", "test")
    for i in range(5):
        p.append("train", i, 1.0 / (i + 1))
    p.append("test", 0, 0.9)
    out = str(tmp_path / "curve.png")
    p.plot(out)
    import os
    assert os.path.exists(out) or os.path.exists(out + ".csv")
    p.save_csv(str(tmp_path / "c.csv"))
    lines = (tmp_path / "c.csv").read_text().strip().splitlines()
    assert len(lines) == 6
    p.reset()
    assert p.data["train"] == ([], [])
