"""Conv/pool/vision op tests vs torch-CPU references (the OpTest pattern:
numpy/torch expected outputs, SURVEY.md §4.1)."""

import numpy as np
import pytest
import torch
import torch.nn.functional as F

import paddle_tpu as fluid


@pytest.mark.parametrize("stride,padding,dilation,groups", [
    (1, 0, 1, 1), (2, 1, 1, 1), (1, 2, 2, 1), (1, 1, 1, 2),
])
def test_conv2d_matches_torch(rng, stride, padding, dilation, groups):
    x = rng.rand(2, 4, 9, 9).astype(np.float32)
    w = rng.rand(6, 4 // groups, 3, 3).astype(np.float32)

    xv = fluid.layers.data("x", [4, 9, 9])
    wv = fluid.layers.data("w", [6, 4 // groups, 3, 3],
                           append_batch_size=False)
    out = fluid.default_main_program().current_block().create_var(
        name="conv_out", dtype="float32")
    fluid.default_main_program().current_block().append_op(
        type="conv2d", inputs={"Input": [xv], "Filter": [wv]},
        outputs={"Output": [out]},
        attrs={"strides": [stride] * 2, "paddings": [padding] * 2,
               "dilations": [dilation] * 2, "groups": groups})

    exe = fluid.Executor(fluid.CPUPlace())
    got, = exe.run(feed={"x": x, "w": w}, fetch_list=[out])
    want = F.conv2d(torch.from_numpy(x), torch.from_numpy(w), None,
                    stride, padding, dilation, groups).numpy()
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("stride,padding", [(1, 0), (2, 1)])
def test_conv2d_transpose_matches_torch(rng, stride, padding):
    x = rng.rand(2, 4, 7, 7).astype(np.float32)
    w = rng.rand(4, 3, 3, 3).astype(np.float32)  # [Cin, Cout, kh, kw]
    xv = fluid.layers.data("x", [4, 7, 7])
    wv = fluid.layers.data("w", [4, 3, 3, 3], append_batch_size=False)
    out = fluid.default_main_program().current_block().create_var(
        name="convt_out", dtype="float32")
    fluid.default_main_program().current_block().append_op(
        type="conv2d_transpose", inputs={"Input": [xv], "Filter": [wv]},
        outputs={"Output": [out]},
        attrs={"strides": [stride] * 2, "paddings": [padding] * 2,
               "dilations": [1, 1], "groups": 1})
    exe = fluid.Executor(fluid.CPUPlace())
    got, = exe.run(feed={"x": x, "w": w}, fetch_list=[out])
    want = F.conv_transpose2d(torch.from_numpy(x), torch.from_numpy(w),
                              None, stride, padding).numpy()
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("ptype", ["max", "avg"])
@pytest.mark.parametrize("ksize,stride,padding,ceil_mode", [
    (2, 2, 0, False), (3, 2, 1, False), (3, 2, 1, True),
])
def test_pool2d_matches_torch(rng, ptype, ksize, stride, padding, ceil_mode):
    x = rng.rand(2, 3, 9, 9).astype(np.float32)
    xv = fluid.layers.data("x", [3, 9, 9])
    out = fluid.layers.pool2d(xv, pool_size=ksize, pool_type=ptype,
                              pool_stride=stride, pool_padding=padding,
                              ceil_mode=ceil_mode, exclusive=False)
    exe = fluid.Executor(fluid.CPUPlace())
    got, = exe.run(feed={"x": x}, fetch_list=[out])
    t = torch.from_numpy(x)
    if ptype == "max":
        want = F.max_pool2d(t, ksize, stride, padding,
                            ceil_mode=ceil_mode).numpy()
    else:
        want = F.avg_pool2d(t, ksize, stride, padding, ceil_mode=ceil_mode,
                            count_include_pad=True).numpy()
    assert got.shape == want.shape
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_global_and_adaptive_pool(rng):
    x = rng.rand(2, 3, 8, 8).astype(np.float32)
    xv = fluid.layers.data("x", [3, 8, 8])
    out = fluid.layers.pool2d(xv, pool_type="avg", global_pooling=True)
    exe = fluid.Executor(fluid.CPUPlace())
    got, = exe.run(feed={"x": x}, fetch_list=[out])
    np.testing.assert_allclose(got.reshape(2, 3), x.mean((2, 3)), rtol=1e-5)


def test_max_pool_with_index_and_unpool(rng):
    x = rng.rand(1, 2, 6, 6).astype(np.float32)
    xv = fluid.layers.data("x", [2, 6, 6])
    blk = fluid.default_main_program().current_block()
    out = blk.create_var(name="p_out", dtype="float32")
    mask = blk.create_var(name="p_mask", dtype="int32")
    blk.append_op(type="max_pool2d_with_index",
                  inputs={"X": [xv]}, outputs={"Out": [out], "Mask": [mask]},
                  attrs={"ksize": [2, 2], "strides": [2, 2],
                         "paddings": [0, 0]})
    un = blk.create_var(name="unpool_out", dtype="float32")
    blk.append_op(type="unpool", inputs={"X": [out], "Indices": [mask]},
                  outputs={"Out": [un]},
                  attrs={"ksize": [2, 2], "strides": [2, 2]})
    exe = fluid.Executor(fluid.CPUPlace())
    got, gmask, gun = exe.run(feed={"x": x}, fetch_list=[out, mask, un])
    tout, tidx = F.max_pool2d(torch.from_numpy(x), 2, 2,
                              return_indices=True)
    np.testing.assert_allclose(got, tout.numpy(), rtol=1e-6)
    np.testing.assert_array_equal(gmask, tidx.numpy())
    tun = F.max_unpool2d(tout, tidx, 2, 2).numpy()
    np.testing.assert_allclose(gun, tun, rtol=1e-6)


def test_max_pool_with_index_negative_input_and_padding(rng):
    # regression: pad cells must never win the max (pad with -inf, not 0)
    x = -1.0 - rng.rand(1, 1, 4, 4).astype(np.float32)
    xv = fluid.layers.data("x", [1, 4, 4])
    blk = fluid.default_main_program().current_block()
    out = blk.create_var(name="p_out", dtype="float32")
    mask = blk.create_var(name="p_mask", dtype="int32")
    blk.append_op(type="max_pool2d_with_index",
                  inputs={"X": [xv]}, outputs={"Out": [out], "Mask": [mask]},
                  attrs={"ksize": [2, 2], "strides": [2, 2],
                         "paddings": [1, 1]})
    exe = fluid.Executor(fluid.CPUPlace())
    got, gmask = exe.run(feed={"x": x}, fetch_list=[out, mask])
    tout, tidx = F.max_pool2d(torch.from_numpy(x), 2, 2, 1,
                              return_indices=True)
    np.testing.assert_allclose(got, tout.numpy(), rtol=1e-6)
    np.testing.assert_array_equal(gmask, tidx.numpy())


def test_unpool_with_padding(rng):
    # 6x6, k=2, s=2, p=1 round-trips exactly through the reference's
    # unpool size formula (in-1)*s - 2p + k
    x = rng.rand(1, 1, 6, 6).astype(np.float32)
    xv = fluid.layers.data("x", [1, 6, 6])
    blk = fluid.default_main_program().current_block()
    out = blk.create_var(name="p_out", dtype="float32")
    mask = blk.create_var(name="p_mask", dtype="int32")
    blk.append_op(type="max_pool2d_with_index",
                  inputs={"X": [xv]}, outputs={"Out": [out], "Mask": [mask]},
                  attrs={"ksize": [2, 2], "strides": [2, 2],
                         "paddings": [1, 1]})
    un = blk.create_var(name="unpool_out", dtype="float32")
    blk.append_op(type="unpool", inputs={"X": [out], "Indices": [mask]},
                  outputs={"Out": [un]},
                  attrs={"ksize": [2, 2], "strides": [2, 2],
                         "paddings": [1, 1]})
    exe = fluid.Executor(fluid.CPUPlace())
    gun, = exe.run(feed={"x": x}, fetch_list=[un])
    tout, tidx = F.max_pool2d(torch.from_numpy(x), 2, 2, 1,
                              return_indices=True)
    tun = F.max_unpool2d(tout, tidx, 2, 2, 1, output_size=(6, 6)).numpy()
    np.testing.assert_allclose(gun, tun, rtol=1e-6)


def test_adaptive_pool_non_divisible(rng):
    x = rng.rand(1, 2, 10, 10).astype(np.float32)
    xv = fluid.layers.data("x", [2, 10, 10])
    blk = fluid.default_main_program().current_block()
    outs = {}
    for ptype in ("max", "avg"):
        o = blk.create_var(name="ap_%s" % ptype, dtype="float32")
        blk.append_op(type="pool2d", inputs={"X": [xv]},
                      outputs={"Out": [o]},
                      attrs={"ksize": [4, 4], "pooling_type": ptype,
                             "adaptive": True})
        outs[ptype] = o
    exe = fluid.Executor(fluid.CPUPlace())
    gmax, gavg = exe.run(feed={"x": x}, fetch_list=[outs["max"],
                                                    outs["avg"]])
    t = torch.from_numpy(x)
    np.testing.assert_allclose(gmax, F.adaptive_max_pool2d(t, 4).numpy(),
                               rtol=1e-6)
    np.testing.assert_allclose(gavg, F.adaptive_avg_pool2d(t, 4).numpy(),
                               rtol=1e-6)


def test_conv_transpose_output_size_enlarge(rng):
    x = rng.rand(1, 4, 7, 7).astype(np.float32)
    w = rng.rand(4, 3, 3, 3).astype(np.float32)
    xv = fluid.layers.data("x", [4, 7, 7])
    wv = fluid.layers.data("w", [4, 3, 3, 3], append_batch_size=False)
    blk = fluid.default_main_program().current_block()
    out = blk.create_var(name="convt_out", dtype="float32")
    blk.append_op(
        type="conv2d_transpose", inputs={"Input": [xv], "Filter": [wv]},
        outputs={"Output": [out]},
        attrs={"strides": [2, 2], "paddings": [0, 0], "dilations": [1, 1],
               "groups": 1, "output_size": [16, 16]})
    exe = fluid.Executor(fluid.CPUPlace())
    got, = exe.run(feed={"x": x, "w": w}, fetch_list=[out])
    want = F.conv_transpose2d(torch.from_numpy(x), torch.from_numpy(w),
                              None, 2, 0, output_padding=1).numpy()
    assert got.shape == (1, 3, 16, 16)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_conv2d_layer_trains(rng):
    img = fluid.layers.data("img", [1, 8, 8])
    label = fluid.layers.data("label", [1], dtype="int64")
    conv = fluid.layers.conv2d(img, num_filters=4, filter_size=3,
                               padding=1, act="relu")
    pool = fluid.layers.pool2d(conv, pool_size=2, pool_stride=2)
    pred = fluid.layers.fc(pool, 10, act="softmax")
    loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    x = rng.rand(16, 1, 8, 8).astype(np.float32)
    y = rng.randint(0, 10, (16, 1)).astype(np.int64)
    losses = []
    for _ in range(12):
        lv, = exe.run(feed={"img": x, "label": y}, fetch_list=[loss])
        losses.append(float(np.asarray(lv)))
    assert losses[-1] < losses[0]


def test_depthwise_conv(rng):
    x = rng.rand(2, 4, 8, 8).astype(np.float32)
    w = rng.rand(4, 1, 3, 3).astype(np.float32)
    xv = fluid.layers.data("x", [4, 8, 8])
    wv = fluid.layers.data("w", [4, 1, 3, 3], append_batch_size=False)
    blk = fluid.default_main_program().current_block()
    out = blk.create_var(name="dw_out", dtype="float32")
    blk.append_op(type="depthwise_conv2d",
                  inputs={"Input": [xv], "Filter": [wv]},
                  outputs={"Output": [out]},
                  attrs={"strides": [1, 1], "paddings": [1, 1],
                         "dilations": [1, 1]})
    exe = fluid.Executor(fluid.CPUPlace())
    got, = exe.run(feed={"x": x, "w": w}, fetch_list=[out])
    want = F.conv2d(torch.from_numpy(x), torch.from_numpy(w), None,
                    1, 1, 1, groups=4).numpy()
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_roi_pool(rng):
    x = np.arange(64, dtype=np.float32).reshape(1, 1, 8, 8)
    rois = np.array([[0, 0, 3, 3], [4, 4, 7, 7]], np.float32)
    xv = fluid.layers.data("x", [1, 8, 8])
    rv = fluid.layers.data("rois", [2, 4], append_batch_size=False)
    out = fluid.layers.roi_pool(xv, rv, pooled_height=2, pooled_width=2,
                                spatial_scale=1.0)
    exe = fluid.Executor(fluid.CPUPlace())
    got, = exe.run(feed={"x": x, "rois": rois}, fetch_list=[out])
    assert got.shape == (2, 1, 2, 2)
    # roi 0 covers rows/cols 0..3: max of each 2x2 quadrant
    np.testing.assert_allclose(got[0, 0], [[9., 11.], [25., 27.]])
