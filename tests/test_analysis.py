"""Golden-diagnostic tests for paddle_tpu.analysis: one deliberately
broken toy fixture per rule (each must FAIL the lint), clean fixtures
that must pass, and the engine/CLI plumbing."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddle_tpu import analysis


def _hits(report, rule, severity=None):
    return [d for d in report
            if d.rule == rule and (severity is None
                                   or d.severity == severity)]


# ---------------------------------------------------------------- R001
def test_dtype_rule_flags_fp16_creep():
    def f(x):
        return x * 2.0

    rep = analysis.check_program(f, np.zeros((8, 8), np.float16))
    assert _hits(rep, "dtype-promotion", analysis.ERROR)


def test_dtype_rule_flags_bf16_softmax_normalizer():
    def f(x):
        e = jnp.exp(x)                     # bf16 exp -> bf16 sum
        return e / jnp.sum(e, -1, keepdims=True)

    rep = analysis.check_program(f, jnp.zeros((8, 128), jnp.bfloat16))
    assert _hits(rep, "dtype-promotion", analysis.ERROR)


def test_dtype_rule_flags_pointless_upcast():
    def f(x):
        y = x.astype(jnp.float32)          # feeds only elementwise ops
        return y * 2.0 + 1.0

    rep = analysis.check_program(f, jnp.zeros((64, 128), jnp.bfloat16))
    assert _hits(rep, "dtype-promotion", analysis.WARNING)


def test_dtype_rule_clean_on_f32_softmax_over_bf16():
    def f(x):
        return jax.nn.softmax(x.astype(jnp.float32), axis=-1)

    rep = analysis.check_program(f, jnp.zeros((8, 128), jnp.bfloat16))
    assert not _hits(rep, "dtype-promotion")


# ---------------------------------------------------------------- R002
def test_recompile_rule_flags_weak_scalar_arg():
    def f(x, scale):
        return x * scale

    rep = analysis.check_program(f, np.zeros((4, 4), np.float32), 3.0)
    found = _hits(rep, "recompile-hazard", analysis.WARNING)
    assert any("weak" in d.message for d in found)


def test_recompile_rule_flags_baked_constant():
    table = np.zeros((1 << 19,), np.float32)        # 2 MiB closure

    def f(idx):
        return jnp.take(jnp.asarray(table), idx)

    rep = analysis.check_program(f, np.zeros((4,), np.int32))
    found = _hits(rep, "recompile-hazard", analysis.WARNING)
    assert any("constant" in d.message for d in found)


# ---------------------------------------------------------------- R003
def test_sharding_rule_flags_replicated_param_and_all_gather():
    from jax.sharding import Mesh, PartitionSpec as P
    from paddle_tpu.parallel._shard_map import shard_map

    mesh = Mesh(np.array(jax.devices()[:2]), ("dp",))

    def body(x, w):
        return jax.lax.psum(x @ w, "dp"), \
            jax.lax.all_gather(x, "dp", tiled=True)

    f = shard_map(body, mesh=mesh,
                  in_specs=(P("dp", None), P(None, None)),
                  out_specs=(P("dp", None), P(None, None)),
                  check_vma=False)
    rep = analysis.check_program(
        f, np.zeros((1024, 512), np.float32),          # 2 MiB act
        np.zeros((512, 512), np.float32))              # 1 MiB param
    found = _hits(rep, "sharding-transfer", analysis.WARNING)
    assert any("replicated" in d.message for d in found)
    assert any("all_gather" in d.message for d in found)


def test_sharding_rule_flags_device_put_of_traced_value():
    def f(x):
        return jax.device_put(x) + 1.0

    rep = analysis.check_program(f, np.zeros((8,), np.float32))
    assert _hits(rep, "sharding-transfer", analysis.WARNING)


# ---------------------------------------------------------------- R004
def test_numerics_rule_flags_unguarded_log_div_rsqrt():
    def f(x, y):
        return (jnp.log(x * y),            # log of a product, no eps
                x / (x * y),               # unguarded denominator
                jax.lax.rsqrt(x * y))      # unguarded rsqrt

    rep = analysis.check_program(f, np.ones((8,), np.float32),
                                 np.ones((8,), np.float32))
    msgs = [d.message for d in _hits(rep, "numerical-risk",
                                     analysis.WARNING)]
    assert any("log" in m for m in msgs)
    assert any("division" in m for m in msgs)
    assert any("rsqrt" in m for m in msgs)


def test_numerics_rule_flags_unshifted_softmax():
    def f(x):
        e = jnp.exp(x)                     # no max-subtraction
        return e / jnp.sum(e, -1, keepdims=True)

    rep = analysis.check_program(f, np.zeros((4, 16), np.float32))
    found = _hits(rep, "numerical-risk", analysis.WARNING)
    assert any("max-subtraction" in d.message for d in found)


def test_numerics_rule_sqrt_guard_depends_on_operand():
    """sqrt preserves zero: x/sqrt(var) is flagged, x/sqrt(var+eps)
    (the batch_norm denominator) is not."""
    def bad(x):
        var = jnp.sum((x - jnp.mean(x)) ** 2)
        return x / jnp.sqrt(var)

    def good(x):
        var = jnp.sum((x - jnp.mean(x)) ** 2)
        return x / jnp.sqrt(var + 1e-5)

    arg = np.ones((8,), np.float32)
    assert _hits(analysis.check_program(bad, arg), "numerical-risk")
    assert not _hits(analysis.check_program(good, arg),
                     "numerical-risk")


def test_numerics_rule_clean_on_guarded_idioms():
    def f(x, mask):
        a = jnp.log(jnp.clip(x, 1e-20))
        b = x / jnp.maximum(jnp.sum(mask), 1.0)
        c = jax.lax.rsqrt(jnp.var(x) + 1e-5)
        d = jax.nn.softmax(x)
        e = jax.nn.log_softmax(x)
        return a, b, c, d, e

    rep = analysis.check_program(f, np.ones((8,), np.float32),
                                 np.ones((8,), np.float32))
    assert not _hits(rep, "numerical-risk")


# ---------------------------------------------------------------- R005
def test_deadcode_rule_flags_unused_param_and_dead_compute():
    def f(params, x):
        wasted = x @ params["w"]           # 512^3 matmul, never used
        del wasted
        return jnp.sum(x), params["dead"]  # dead: pass-through only

    params = {"w": np.zeros((512, 512), np.float32),
              "dead": np.zeros((4,), np.float32)}
    rep = analysis.check_program(f, params, np.zeros((512, 512),
                                                     np.float32))
    found = _hits(rep, "dead-code", analysis.WARNING)
    assert any("dead" in d.message and "args[0]['dead']" in d.message
               for d in found)
    assert any("dead eqn" in d.message for d in found)


# ---------------------------------------------------------------- R006
def test_cost_rule_reports_hotspot_and_flags_dominant_eqn():
    def f(a, b):
        return a @ b                       # 2 * 1024^3 > hot_flops

    rep = analysis.check_program(f, np.zeros((1024, 1024), np.float32),
                                 np.zeros((1024, 1024), np.float32))
    hot = _hits(rep, "cost-model", analysis.WARNING)
    assert hot and hot[0].cost_flops == 2.0 * 1024 ** 3
    assert any("static cost" in d.message
               for d in _hits(rep, "cost-model", analysis.INFO))


def test_cost_rule_weights_scan_bodies_by_trip_count():
    def f(x):
        def body(c, _):
            return c @ x, ()
        out, _ = jax.lax.scan(body, x, None, length=8)
        return out

    rep = analysis.check_program(
        f, np.zeros((128, 128), np.float32),
        rules=["cost-model"])
    summary = [d for d in rep if "static cost" in d.message][0]
    # 8 iterations x 2*128^3 FLOPs, reported in MFLOPs
    assert "33.55 MFLOP" in summary.message


# ------------------------------------------------------- engine / API
def test_op_paths_point_back_at_program_ops():
    """The executor scopes each op lowering as <op_type>.<seq>, so
    analyzer paths identify the source Program op."""
    from paddle_tpu.models import zoo_entry
    fn, args = zoo_entry("mlp")
    a = analysis.Analysis(fn, args, name="mlp")
    paths = {view.eqn_path(eqn) for view, eqn in a.iter_eqns()}
    assert any("mul." in p and "dot_general" in p for p in paths)
    assert any("adam." in p for p in paths)


def test_custom_rule_registration_and_selection():
    class NitRule(analysis.Rule):
        name = "nit"
        id = "R999"
        doc = "flags every add"

        def check(self, a):
            for view, eqn in a.iter_eqns():
                if eqn.primitive.name == "add":
                    yield analysis.Diagnostic(
                        self.name, analysis.INFO, "an add",
                        path=view.eqn_path(eqn))

    analysis.register_rule(NitRule)
    try:
        rep = analysis.check_program(
            lambda x: x + 1.0, np.zeros((2,), np.float32),
            rules=["nit"])
        assert _hits(rep, "nit")
        assert not _hits(rep, "cost-model")   # only requested rules ran
    finally:
        analysis.engine._RULES.pop("nit", None)
    with pytest.raises(KeyError):
        analysis.check_program(lambda x: x, np.zeros(1),
                               rules=["no-such-rule"])


def test_report_json_and_severity_filters():
    rep = analysis.check_program(
        lambda x: jnp.log(x * x), np.ones((4,), np.float32))
    import json
    blob = json.loads(rep.to_json())
    assert set(blob["counts"]) == {"error", "warning", "info"}
    assert blob["diagnostics"]
    assert len(rep.at_least("info")) == len(rep)
    assert all(d.severity == "warning"
               for d in rep.by_severity("warning"))


def test_cli_list_flags():
    from paddle_tpu.analysis.__main__ import main
    assert main(["--list-rules"]) == 0
    assert main(["--list-models"]) == 0
