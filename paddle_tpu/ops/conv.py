"""Convolution / pooling / vision ops.

Reference parity: operators/conv_op.cc (+cudnn), conv_transpose_op.cc,
pool_op.cc, pool_with_index_op.cc, unpool_op.cc, spp_op.cc, roi_pool_op.cc,
row_conv_op.cc, operators/math/{im2col,vol2col,pooling,depthwise_conv}.

TPU-first: every conv lowers to a single ``lax.conv_general_dilated`` — the
op XLA tiles directly onto the MXU — instead of the reference's
im2col+GEMM / cuDNN dispatch. Data layout attr is honoured (NCHW default for
API parity); XLA relayouts internally for the TPU's preferred tiling, so no
manual NHWC conversion is needed. Grouped and depthwise convs use
``feature_group_count`` (no separate depthwise kernel like
math/depthwise_conv.cu).
"""

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register


def _pair(v, n=2):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v),) * n


def _conv_dnums(ndim, layout):
    # (lhs, rhs, out) dimension-number strings for 1/2/3-d convs.
    sp = "DHW"[-(ndim - 2):] if ndim > 2 else ""
    if layout == "NHWC":
        lhs = "N" + sp + "C"
    else:
        lhs = "NC" + sp
    return lax.conv_dimension_numbers((1,) * ndim, (1,) * ndim,
                                      (lhs, "OI" + sp, lhs))


def _find_train_bn_consumer(ctx, out_name):
    """The batch_norm op (train mode) consuming `out_name`, if any —
    the conv+BN stat-fusion pattern (matmul_stats.py)."""
    block = getattr(ctx, "block", None)
    if block is None or getattr(ctx, "is_test", False):
        return None
    for o in block.ops:
        if o.type == "batch_norm" and o.input("X") == [out_name] \
                and not o.attr("is_test", False):
            return o
    return None


def _conv_nd(ctx, op, ndim):
    x = ctx.in1(op, "Input")
    w = ctx.in1(op, "Filter")
    out_dtype = x.dtype
    from ..amp import maybe_bf16
    x, w = maybe_bf16(x, w)
    strides = _pair(op.attr("strides", [1] * (ndim - 2)), ndim - 2)
    paddings = _pair(op.attr("paddings", [0] * (ndim - 2)), ndim - 2)
    dilations = _pair(op.attr("dilations", [1] * (ndim - 2)), ndim - 2)
    groups = int(op.attr("groups", 1) or 1)
    layout = op.attr("data_format", op.attr("data_layout", "NCHW"))
    layout = "NHWC" if layout in ("NHWC", "NDHWC") else "NCHW"
    if ndim == 4 and _maybe_conv1x1_bn_fused(
            ctx, op, x, w, strides, paddings, dilations, groups, layout,
            out_dtype):
        return
    dn = _conv_dnums(ndim, layout)
    pad = [(p, p) for p in paddings]
    # bf16 path: all-bf16 with pet=None. On TPU the MXU accumulates bf16
    # dots in fp32 internally regardless of preferred_element_type (pet only
    # selects the RESULT dtype), and an explicit fp32 pet breaks jax's conv
    # vjp on bf16 inputs (mixed-dtype transpose conv) — so bf16 training
    # requires this form; only the final rounding to bf16 differs.
    pet = None if x.dtype == jnp.bfloat16 else (
        x.dtype if x.dtype == jnp.float64 else jnp.float32)
    out = lax.conv_general_dilated(
        x, w, window_strides=strides, padding=pad,
        lhs_dilation=(1,) * (ndim - 2), rhs_dilation=dilations,
        dimension_numbers=dn, feature_group_count=groups,
        preferred_element_type=pet)
    from ..amp import amp_out
    ctx.set_out(op, "Output", amp_out(out, out_dtype))


def _maybe_conv1x1_bn_fused(ctx, op, x, w, strides, paddings, dilations,
                            groups, layout, out_dtype):
    """1x1-conv + train-BN stat fusion: the conv runs as a Pallas matmul
    whose epilogue accumulates the per-channel shifted stats BN needs
    (matmul_colstats), eliminating BN's extra read of the conv output —
    the measured ~16 ms/step ResNet stat tax (PERF.md round-3
    breakdown). The stats ride to the consumer BN via a ctx.env stash.
    Returns True when it handled the op."""
    # default OFF: the fusion was built for the ResNet BN stat tax, but
    # the measured result went the other way — the Pallas matmul (the
    # fusion vehicle) loses more against XLA's conv at the bandwidth-
    # bound 1x1 shapes than the fused stats save (full model: 1134 vs
    # 2491 img/s; per-shape: benchmarks/perf_probe_mmstats.py). Kept as
    # an opt-in and as the committed evidence for that conclusion
    # (PERF.md round-4 "ResNet conv+BN fusion probe").
    from ..flags import get_flag
    if not get_flag("fuse_conv_bn"):
        return False
    if (groups != 1 or layout != "NCHW" or w.shape[2:] != (1, 1)
            or any(p != 0 for p in paddings)
            or any(d != 1 for d in dilations)):
        return False
    out_name = op.output("Output")[0]
    bn = _find_train_bn_consumer(ctx, out_name)
    if bn is None:
        return False
    mean_names = bn.input("Mean")
    if not mean_names or ctx.env.get(mean_names[0]) is None:
        return False
    from .matmul_stats import matmul_colstats
    co, ci = int(w.shape[0]), int(w.shape[1])
    sh, sw = strides
    if sh != 1 or sw != 1:
        x = x[:, :, ::sh, ::sw]        # 1x1 stride = spatial subsample
    n, _, hh, ww = x.shape
    c = jax.lax.stop_gradient(
        ctx.env[mean_names[0]].astype(jnp.float32).reshape(co))
    xt = jnp.transpose(x, (0, 2, 3, 1)).reshape(-1, ci)
    y2, s1, s2 = matmul_colstats(xt, w.reshape(co, ci).T, c)
    out = jnp.transpose(y2.reshape(n, hh, ww, co), (0, 3, 1, 2))
    from ..amp import amp_out
    ctx.env[out_name + "@BNSTATS"] = (s1, s2)
    ctx.set_out(op, "Output", amp_out(out, out_dtype))
    return True


@register("conv2d")
def _conv2d(ctx, op):
    _conv_nd(ctx, op, 4)


@register("conv3d")
def _conv3d(ctx, op):
    _conv_nd(ctx, op, 5)


@register("depthwise_conv2d")
def _depthwise_conv2d(ctx, op):
    # filter [C*mult, 1, kh, kw], groups == C (conv_op.cc depthwise path)
    x = ctx.in1(op, "Input")
    op.attrs = dict(op.attrs)
    op.attrs["groups"] = int(x.shape[1])
    _conv_nd(ctx, op, 4)


def _conv_transpose_nd(ctx, op, ndim):
    # Reference filter layout [C_in, C_out/groups, kH, kW]
    # (conv_transpose_op.cc). Lower as the gradient-of-conv: input dilation.
    x = ctx.in1(op, "Input")
    w = ctx.in1(op, "Filter")
    nsp = ndim - 2
    strides = _pair(op.attr("strides", [1] * nsp), nsp)
    paddings = _pair(op.attr("paddings", [0] * nsp), nsp)
    dilations = _pair(op.attr("dilations", [1] * nsp), nsp)
    groups = int(op.attr("groups", 1) or 1)
    out_dtype = x.dtype
    from ..amp import maybe_bf16
    x, w = maybe_bf16(x, w)
    # transpose-conv == conv with lhs_dilation=stride, flipped kernel,
    # padding (k-1)*d - p on each side
    sp_axes = tuple(range(2, ndim))
    w_flip = jnp.flip(w, sp_axes)
    # [Cin, Cout/g, k...] -> [Cout, Cin/g, k...]
    if groups == 1:
        w_t = jnp.swapaxes(w_flip, 0, 1)
    else:
        cin, cog = w.shape[0], w.shape[1]
        w_g = w_flip.reshape((groups, cin // groups, cog) + w.shape[2:])
        w_g = jnp.swapaxes(w_g, 1, 2)  # [g, cog, cin/g, k...]
        w_t = w_g.reshape((groups * cog, cin // groups) + w.shape[2:])
    pad = [((w.shape[2 + i] - 1) * dilations[i] - paddings[i],) * 2
           for i in range(nsp)]
    dn = _conv_dnums(ndim, "NCHW")
    out = lax.conv_general_dilated(
        x, w_t, window_strides=(1,) * nsp, padding=pad,
        lhs_dilation=strides, rhs_dilation=dilations,
        dimension_numbers=dn, feature_group_count=groups)
    out_size = op.attr("output_size")
    if out_size:
        # Paddle allows output_size in [minimal, minimal+stride): shrink by
        # slicing, enlarge by bottom/right zero-pad (conv_transpose_op.cc).
        out = out[(Ellipsis,) + tuple(slice(0, int(s)) for s in out_size)]
        pad = [(0, 0), (0, 0)] + [
            (0, max(0, int(s) - out.shape[2 + i]))
            for i, s in enumerate(out_size)]
        out = jnp.pad(out, pad)
    from ..amp import amp_out
    ctx.set_out(op, "Output", amp_out(out, out_dtype))


@register("conv2d_transpose")
def _conv2d_transpose(ctx, op):
    _conv_transpose_nd(ctx, op, 4)


@register("conv3d_transpose")
def _conv3d_transpose(ctx, op):
    _conv_transpose_nd(ctx, op, 5)


# --------------------------------------------------------------------------
# pooling
# --------------------------------------------------------------------------

def _pool_out(x, ksize, strides, paddings, pooling_type, ceil_mode,
              exclusive, global_pooling, adaptive):
    n_sp = len(ksize)
    sp_shape = x.shape[2:]
    if global_pooling:
        ksize = tuple(sp_shape)
        paddings = (0,) * n_sp
        strides = tuple(sp_shape)
    if adaptive:
        return _adaptive_pool(x, ksize, pooling_type)
    window = (1, 1) + tuple(ksize)
    strd = (1, 1) + tuple(strides)
    if ceil_mode:
        # pad the right edge so the last partial window is included
        extra = []
        for i in range(n_sp):
            span = sp_shape[i] + 2 * paddings[i] - ksize[i]
            rem = span % strides[i]
            extra.append((strides[i] - rem) % strides[i] if rem else 0)
        pad = [(0, 0), (0, 0)] + [(paddings[i], paddings[i] + extra[i])
                                  for i in range(n_sp)]
    else:
        pad = [(0, 0), (0, 0)] + [(p, p) for p in paddings]

    if pooling_type == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) \
            else jnp.iinfo(x.dtype).min
        return lax.reduce_window(x, init, lax.max, window, strd, pad)
    # avg
    s = lax.reduce_window(x.astype(jnp.float32), 0.0, lax.add, window, strd,
                          pad)
    if exclusive or any(p[0] or p[1] for p in pad[2:]):
        ones = jnp.ones(x.shape, jnp.float32)
        cnt = lax.reduce_window(ones, 0.0, lax.add, window, strd, pad)
        if not exclusive:
            cnt = jnp.maximum(cnt, float(np.prod(ksize)))
        out = s / jnp.maximum(cnt, 1.0)
    else:
        out = s / float(np.prod(ksize))
    return out.astype(x.dtype)


def _adaptive_pool(x, out_sz, pooling_type):
    """Adaptive pooling with Paddle's bin rule: bin i spans
    [floor(i*S/o), ceil((i+1)*S/o)) (pool_op.cc AdaptiveStartIndex/EndIndex).
    Lowered as per-axis mask reductions so the output size is exact for
    non-divisible sizes too."""
    sp_shape = x.shape[2:]
    out = x
    for ax, (size, o) in enumerate(zip(sp_shape, out_sz)):
        i = np.arange(o)
        starts = (i * size) // o
        ends = -(-((i + 1) * size) // o)
        pos = np.arange(size)
        mask = (pos[None, :] >= starts[:, None]) & (pos[None, :] < ends[:, None])
        axis = 2 + ax
        # move target axis last, reduce against mask, put bin axis back
        moved = jnp.moveaxis(out, axis, -1)[..., None, :]    # [..., 1, S]
        m = jnp.asarray(mask)                                # [o, S]
        if pooling_type == "max":
            red = jnp.max(jnp.where(m, moved, -jnp.inf), axis=-1)
        else:
            cnt = (ends - starts).astype(np.float32)
            red = jnp.sum(jnp.where(m, moved, 0.0), axis=-1) / \
                jnp.asarray(cnt, out.dtype)
        out = jnp.moveaxis(red, -1, axis)
    return out.astype(x.dtype)


def _pool_nd(ctx, op, n_sp):
    x = ctx.in1(op, "X")
    ksize = _pair(op.attr("ksize", [1] * n_sp), n_sp)
    strides = _pair(op.attr("strides", [1] * n_sp), n_sp)
    paddings = _pair(op.attr("paddings", [0] * n_sp), n_sp)
    if op.attr("adaptive", False):
        out = _adaptive_pool(x, ksize, op.attr("pooling_type", "max"))
    else:
        out = _pool_out(x, ksize, strides, paddings,
                        op.attr("pooling_type", "max"),
                        op.attr("ceil_mode", False),
                        op.attr("exclusive", True),
                        op.attr("global_pooling", False), False)
    ctx.set_out(op, "Out", out)


@register("pool2d")
def _pool2d(ctx, op):
    _pool_nd(ctx, op, 2)


@register("pool3d")
def _pool3d(ctx, op):
    _pool_nd(ctx, op, 3)


def _extract_patches(x, ksize, strides, paddings):
    """[N,C,H,W] -> (patches [N,C,kh*kw,Ho,Wo], flat spatial index of each
    patch element [N,C,kh*kw,Ho,Wo]). Padding is applied here with -inf on
    values (so pad cells never win a max) and -1 on indices."""
    n, c, h, w = x.shape
    kh, kw = ksize
    ph, pw = paddings
    # finite lowest value, not -inf: patch extraction is a one-hot conv and
    # -inf * 0 would poison every patch with NaN
    if jnp.issubdtype(x.dtype, jnp.floating):
        lowest = float(jnp.finfo(x.dtype).min)
    else:
        lowest = int(jnp.iinfo(x.dtype).min)
    xp = jnp.pad(x, ((0, 0), (0, 0), (ph, ph), (pw, pw)),
                 constant_values=lowest)
    # HIGHEST precision: patch extraction is pure data movement (a
    # one-hot conv); the TPU's default bf16 MXU pass would QUANTIZE the
    # copied values, corrupting pooled maxima
    patches = lax.conv_general_dilated_patches(
        xp, filter_shape=ksize, window_strides=strides,
        padding=[(0, 0), (0, 0)], dimension_numbers=("NCHW", "OIHW", "NCHW"),
        precision=lax.Precision.HIGHEST)
    # patches: [N, C*kh*kw, Ho, Wo]
    ho, wo = patches.shape[2], patches.shape[3]
    patches = patches.reshape(n, c, kh * kw, ho, wo)
    # analytic index map (exact int32; a float conv would lose precision
    # above 2**24): element (ki,kj) of the patch at output (oh,ow) sits at
    # input position (oh*sh - ph + ki, ow*sw - pw + kj)
    sh, sw = strides
    oh = jnp.arange(ho)[:, None, None, None]
    ow = jnp.arange(wo)[None, :, None, None]
    ki = jnp.arange(kh)[None, None, :, None]
    kj = jnp.arange(kw)[None, None, None, :]
    iy = oh * sh - ph + ki
    ix = ow * sw - pw + kj
    valid = (iy >= 0) & (iy < h) & (ix >= 0) & (ix < w)
    flat = jnp.where(valid, iy * w + ix, -1)        # [Ho, Wo, kh, kw]
    ipatch = jnp.transpose(flat.reshape(ho, wo, kh * kw), (2, 0, 1))
    ipatch = ipatch[None, None].astype(jnp.int32)   # [1,1,kh*kw,Ho,Wo]
    return patches, ipatch


@register("max_pool2d_with_index")
def _max_pool2d_with_index(ctx, op):
    # pool_with_index_op.cc: returns pooled values + flat spatial argmax
    x = ctx.in1(op, "X")
    ksize = _pair(op.attr("ksize", [2, 2]))
    strides = _pair(op.attr("strides", [2, 2]))
    paddings = _pair(op.attr("paddings", [0, 0]))
    if op.attr("global_pooling", False):
        ksize = x.shape[2:]
        strides = ksize
        paddings = (0, 0)
    patches, ipatch = _extract_patches(x, ksize, strides, paddings)
    amax = jnp.argmax(patches, axis=2)
    out = jnp.max(patches, axis=2)
    idx = jnp.take_along_axis(
        jnp.broadcast_to(ipatch, patches.shape[:2] + ipatch.shape[2:]),
        amax[:, :, None], axis=2)[:, :, 0]
    ctx.set_out(op, "Out", out)
    ctx.set_out(op, "Mask", idx.astype(jnp.int32))


@register("unpool")
def _unpool(ctx, op):
    # unpool_op.cc: scatter pooled values back to argmax positions
    x = ctx.in1(op, "X")
    mask = ctx.in1(op, "Indices")
    n, c, ho, wo = x.shape
    ksize = _pair(op.attr("ksize", [2, 2]))
    strides = _pair(op.attr("strides", ksize))
    paddings = _pair(op.attr("paddings", [0, 0]))
    # unpool_op.cc: H_out = (H_in-1)*stride - 2*pad + ksize
    h = (ho - 1) * strides[0] - 2 * paddings[0] + ksize[0]
    w = (wo - 1) * strides[1] - 2 * paddings[1] + ksize[1]
    flat = jnp.zeros((n, c, h * w), x.dtype)
    idx = mask.reshape(n, c, ho * wo).astype(jnp.int32)
    vals = x.reshape(n, c, ho * wo)
    out = jax.vmap(jax.vmap(lambda f, i, v: f.at[i].set(v)))(flat, idx, vals)
    ctx.set_out(op, "Out", out.reshape(n, c, h, w))


@register("spp")
def _spp(ctx, op):
    # spp_op.cc: spatial pyramid pooling — concat of pyramid_height adaptive
    # pools flattened per level
    x = ctx.in1(op, "X")
    levels = int(op.attr("pyramid_height", 1))
    ptype = op.attr("pooling_type", "max")
    n = x.shape[0]
    outs = []
    for lvl in range(levels):
        bins = 2 ** lvl
        h, w = x.shape[2], x.shape[3]
        kh, kw = -(-h // bins), -(-w // bins)
        sh, sw = kh, kw
        ph = max(0, (bins * kh - h + 1) // 2)
        pw = max(0, (bins * kw - w + 1) // 2)
        pooled = _pool_out(x, (kh, kw), (sh, sw), (ph, pw), ptype,
                           False, False, False, False)
        outs.append(pooled.reshape(n, -1))
    ctx.set_out(op, "Out", jnp.concatenate(outs, axis=1))


@register("roi_pool")
def _roi_pool(ctx, op):
    # roi_pool_op.cc: max-pool each ROI into pooled_h x pooled_w bins
    x = ctx.in1(op, "X")
    rois = ctx.in1(op, "ROIs")          # [R, 4] (x1,y1,x2,y2)
    ph = int(op.attr("pooled_height", 1))
    pw = int(op.attr("pooled_width", 1))
    scale = float(op.attr("spatial_scale", 1.0))
    n, c, h, w = x.shape
    r = rois.shape[0]
    lod = ctx.maybe_get(op.input("ROIs")[0] + "@LOD")
    if lod is not None:
        batch_idx = jnp.repeat(jnp.arange(lod.shape[0]), lod,
                               total_repeat_length=r)
    else:
        batch_idx = jnp.zeros((r,), jnp.int32)

    ys = jnp.arange(h, dtype=jnp.float32)
    xs = jnp.arange(w, dtype=jnp.float32)

    def one_roi(roi, bi):
        x1 = jnp.round(roi[0] * scale)
        y1 = jnp.round(roi[1] * scale)
        x2 = jnp.round(roi[2] * scale)
        y2 = jnp.round(roi[3] * scale)
        rh = jnp.maximum(y2 - y1 + 1, 1.0)
        rw = jnp.maximum(x2 - x1 + 1, 1.0)
        bh, bw = rh / ph, rw / pw
        img = x[bi]                                   # [C,H,W]

        def bin_val(i, j):
            ys0 = jnp.floor(y1 + i * bh)
            ys1 = jnp.ceil(y1 + (i + 1) * bh)
            xs0 = jnp.floor(x1 + j * bw)
            xs1 = jnp.ceil(x1 + (j + 1) * bw)
            my = (ys >= ys0) & (ys < jnp.maximum(ys1, ys0 + 1))
            mx = (xs >= xs0) & (xs < jnp.maximum(xs1, xs0 + 1))
            m = my[:, None] & mx[None, :]
            return jnp.max(jnp.where(m[None], img, -jnp.inf), axis=(1, 2))

        ii, jj = jnp.meshgrid(jnp.arange(ph), jnp.arange(pw), indexing="ij")
        vals = jax.vmap(jax.vmap(bin_val))(ii.astype(jnp.float32),
                                           jj.astype(jnp.float32))
        # vals: [ph, pw, C] -> [C, ph, pw]
        out = jnp.transpose(vals, (2, 0, 1))
        return jnp.where(jnp.isfinite(out), out, 0.0)

    out = jax.vmap(one_roi)(rois.astype(jnp.float32), batch_idx)
    ctx.set_out(op, "Out", out.astype(x.dtype))


@register("row_conv")
def _row_conv(ctx, op):
    # row_conv_op.cc: lookahead conv over time for each sequence.
    # x [T, D] flat sequences, filter [future_context+1, D].
    x = ctx.in1(op, "X")
    w = ctx.in1(op, "Filter")
    k = w.shape[0]
    lengths = ctx.maybe_get(op.input("X")[0] + "@LOD")
    xp = jnp.pad(x, ((0, k - 1), (0, 0)))
    stacked = jnp.stack([xp[i:i + x.shape[0]] for i in range(k)], axis=0)
    out = jnp.einsum("ktd,kd->td", stacked, w)
    if lengths is not None:
        # zero out lookahead crossing sequence boundaries
        ends = jnp.cumsum(lengths)
        seg = jnp.searchsorted(ends, jnp.arange(x.shape[0]), side="right")
        seg_p = jnp.pad(seg, (0, k - 1), constant_values=seg[-1] + 1 if
                        x.shape[0] else 0)
        contrib = jnp.stack(
            [jnp.where((seg_p[i:i + x.shape[0]] == seg)[:, None],
                       xp[i:i + x.shape[0]], 0.0) for i in range(k)], axis=0)
        out = jnp.einsum("ktd,kd->td", contrib, w)
    ctx.set_out(op, "Out", out)
